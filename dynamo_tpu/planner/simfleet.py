"""Fleet-scale elasticity simulation: a deterministic mock fleet driving
the REAL control plane.

The chaos soak the elasticity loop is proven against needs 50–100 workers
under bursty open-loop traffic with seeded kills, drains, and an overload
wave — far past what subprocess clusters can do inside the tier-1 budget.
This harness simulates only the parts that are honestly simulable (token
generation cadence, prefill latency, wall time) and runs the REAL
machinery for everything the soak actually asserts about:

  * placement — the real :class:`KvScheduler` (load-aware cost model,
    drain deflection, candidate pruning) routes every request;
  * crash detection — the real :class:`LivenessTracker` (fake clock =
    sim clock) declares silence-shaped deaths and fires the real
    ``drop_worker`` reconciliation;
  * sizing — the real :class:`Planner` + :class:`ElasticController`
    observe the simulated SLA metrics and actuate scale-up/scale-down
    through this fleet's ``launch``/``wait_ready``/``drain`` surface
    (:class:`SimFleet` implements the elastic controller's Fleet
    protocol).

**Token-exactness is structural, not assumed.** Each stream's tokens come
from a fold chain — ``state₀ = H(rid)``, ``tokenᵢ = f(stateᵢ)``,
``stateᵢ₊₁ = fold(stateᵢ, tokenᵢ)`` — the same shape as the engine's
``fold_in(seed, salt, pos)`` contract. A handoff carries the fold state
verbatim (KV moved); a kill-9 migration RECONSTRUCTS it by re-folding the
carried tokens (re-prefill). Any bookkeeping bug — a lost, duplicated, or
reordered token across a migration/handoff — shifts the state and every
subsequent token diverges from :func:`expected_tokens`, so "zero lost
streams, token-exact" is a real claim about the churn machinery, not a
tautology.

Time is simulated (``SimFleet.now``); a 100-worker, minutes-of-sim-time
soak runs in wall seconds and replays bit-identically from its seed.
"""

from __future__ import annotations

import random
import statistics
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from dynamo_tpu.planner.perf_interpolation import (
    DecodeInterpolator,
    PrefillInterpolator,
)
from dynamo_tpu.planner.planner_core import MetricsSnapshot
from dynamo_tpu.router.protocols import LoadSnapshot, WorkerKey
from dynamo_tpu.router.scheduler import KvRouterConfig, KvScheduler
from dynamo_tpu.runtime.liveness import LivenessConfig, LivenessTracker
from dynamo_tpu.tokens.radix import OverlapScores
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_MASK = (1 << 64) - 1
_VOCAB = 50257


def _seed_state(rid: str) -> int:
    state = 0xCBF29CE484222325
    for ch in rid.encode():
        state = ((state ^ ch) * 0x100000001B3) & _MASK
    return state


def _fold(state: int, token: int) -> int:
    return (state * 6364136223846793005 + token + 1442695040888963407) & _MASK


def _token_of(state: int) -> int:
    return (state >> 33) % _VOCAB


def expected_tokens(rid: str, osl: int) -> List[int]:
    """The oracle: what a never-disturbed worker would generate."""
    state = _seed_state(rid)
    out = []
    for _ in range(osl):
        tok = _token_of(state)
        out.append(tok)
        state = _fold(state, tok)
    return out


@dataclass
class SimConfig:
    seed: int = 0
    block_size: int = 16
    blocks_per_worker: int = 4096
    # ITL-SLA sweet spot: at/below this many concurrent streams a worker
    # decodes at base_itl_s; above it, ITL degrades linearly (the shape of
    # a batch-bound decode worker past its roofline).
    worker_max_conc: int = 8
    # Hard admission cap (engine max_num_seqs analog): the sim worker
    # refuses past this; refused requests sit in the fleet backlog.
    hard_cap_factor: int = 4
    base_itl_s: float = 0.02
    base_ttft_s: float = 0.2
    # Truth multiplier on both latencies: the fleet the planner actually
    # has. A profile built with ``profile_error=2`` while speed_factor=1
    # claims workers 2× faster than they are — the mis-profile the
    # correction-factor feedback must heal.
    speed_factor: float = 1.0
    report_interval_s: float = 0.25
    substep_s: float = 0.05
    liveness_suspect_after: int = 2
    liveness_dead_after: int = 4
    isl: int = 256
    osl: int = 64
    # Scale-up latency: launch → /readyz green (process start + engine +
    # warm restore).
    launch_delay_s: float = 1.0
    # Handoff adoption pause on the receiving worker (ticket + KV install).
    handoff_pause_s: float = 0.05
    router: Optional[KvRouterConfig] = None

    @property
    def hard_cap(self) -> int:
        return self.worker_max_conc * self.hard_cap_factor

    def itl_of(self, concurrency: int) -> float:
        return (
            self.base_itl_s
            * self.speed_factor
            * max(1.0, concurrency / self.worker_max_conc)
        )

    def ttft_of(self, isl: int) -> float:
        return self.base_ttft_s * self.speed_factor * (isl / max(self.isl, 1))


def profile_interpolators(
    cfg: SimConfig, *, error: float = 1.0
) -> Tuple[PrefillInterpolator, DecodeInterpolator]:
    """Build the planner's interpolation table from the sim's truth,
    optionally mis-profiled: ``error=2`` claims the fleet 2× FASTER than
    it is (half the TTFT/ITL, double the throughput) — the planner then
    undersizes until correction-factor feedback folds the observed ratio
    back in."""
    isls = [cfg.isl // 4, cfg.isl, cfg.isl * 4]
    ttfts = [cfg.ttft_of(i) / error for i in isls]
    prefill = PrefillInterpolator(
        isls, ttfts, [i / t for i, t in zip(isls, ttfts)]
    )
    concs = [1, cfg.worker_max_conc, cfg.worker_max_conc * 2,
             cfg.worker_max_conc * 4]
    itls = [cfg.itl_of(c) / error for c in concs]
    decode = DecodeInterpolator(
        concs, itls, [c / i for c, i in zip(concs, itls)]
    )
    return prefill, decode


@dataclass
class SimStream:
    rid: str
    isl: int
    osl: int
    arrived: float
    state: int
    tokens: List[int] = field(default_factory=list)
    acc: float = 0.0  # fractional decode progress
    worker: Optional[int] = None
    prefill_until: float = 0.0  # prefill/adoption gate on current worker
    first_token_at: Optional[float] = None
    done_at: Optional[float] = None
    migrations: int = 0
    handoffs: int = 0
    charged_worker: Optional[int] = None
    charged_blocks: int = 0
    report_gen: int = 0
    block_size: int = 16

    @property
    def blocks(self) -> int:
        return (self.isl + len(self.tokens)) // self.block_size + 1


@dataclass
class SimWorker:
    wid: int
    incarnation: int
    ready_at: float
    alive: bool = True
    draining: bool = False
    streams: Dict[str, SimStream] = field(default_factory=dict)

    def ready(self, now: float) -> bool:
        return self.alive and not self.draining and now >= self.ready_at


class SimFleet:
    """The simulated fleet + the real control plane around it. Implements
    the ElasticController's Fleet protocol (``ready_count`` / ``load_view``
    / ``launch`` / ``wait_ready`` / ``drain``)."""

    def __init__(
        self,
        cfg: SimConfig,
        *,
        n_workers: int = 4,
        rate_fn: Optional[Callable[[float], float]] = None,
    ) -> None:
        self.cfg = cfg
        self.now = 0.0
        self.rate_fn = rate_fn or (lambda _t: 0.0)
        self.scheduler = KvScheduler(
            cfg.router or KvRouterConfig(), seed=cfg.seed
        )
        self.tracker = LivenessTracker(
            LivenessConfig(
                interval_s=cfg.report_interval_s,
                suspect_after=cfg.liveness_suspect_after,
                dead_after=cfg.liveness_dead_after,
            ),
            clock=lambda: self.now,
            on_dead=self._on_dead,
        )
        self.rng = random.Random(cfg.seed)
        self.workers: Dict[int, SimWorker] = {}
        # Routable = registered in discovery: ready workers AND silent
        # corpses the liveness plane hasn't evicted yet (routing to a
        # corpse until detection is the behavior under test, not a bug).
        self._routable: set = set()
        self.backlog: Deque[SimStream] = deque()
        self.completed: List[SimStream] = []
        self._interval_done: List[SimStream] = []
        self._interval_arrivals = 0
        self._interval_started = 0.0
        self._arrival_acc = 0.0
        self._next_report = 0.0
        self._next_wid = 1
        self.arrivals = 0
        # Chaos bookkeeping the soak asserts over.
        self.killed: set = set()
        self.retired: List[int] = []
        self.false_positive_deaths: List[int] = []
        self.detection_latencies: List[float] = []
        self.reprefill_tokens = 0
        # Re-prefill attributable to DRAIN fallbacks specifically: the
        # zero-re-prefill elasticity claim is about this staying 0
        # whenever peers exist (kill-9 migrations legitimately re-prefill).
        self.drain_reprefill_tokens = 0
        self.handoff_streams = 0
        self.migrated_streams = 0
        self.requeues = 0
        self._last_killed: List[int] = []
        self._chaos: List[Tuple[float, str, Any]] = []
        self._chaos_fired = 0
        self._overload_until = 0.0
        self._overload_mult = 1.0
        self.events: List[Tuple[float, str, Any]] = []
        for _ in range(n_workers):
            self._spawn(ready_in=0.0)

    # -- fleet membership ----------------------------------------------------

    def _spawn(self, ready_in: float, wid: Optional[int] = None,
               incarnation: int = 1) -> SimWorker:
        if wid is None:
            wid = self._next_wid
            self._next_wid += 1
        w = SimWorker(
            wid=wid, incarnation=incarnation, ready_at=self.now + ready_in
        )
        self.workers[wid] = w
        return w

    def schedule_chaos(self, events: List[Tuple[float, str, Any]]) -> None:
        """``(t, kind, arg)`` with kind ∈ kill | restart | drain |
        overload. ``arg=None`` picks a victim from the live fleet with the
        seeded rng at fire time (restart revives the oldest unrestarted
        kill); overload's arg is ``(duration_s, rate_multiplier)``."""
        self._chaos = sorted(self._chaos + events, key=lambda e: e[0])

    def kill(self, wid: int) -> None:
        """kill -9: the worker goes SILENT. It stays routable (discovery
        still lists it) until the liveness plane declares it dead — its
        frozen streams stall exactly as a real corpse's would."""
        w = self.workers[wid]
        w.alive = False
        self.killed.add(wid)
        self._last_killed.append(wid)
        self.events.append((self.now, "kill", wid))

    def restart(self, wid: int) -> None:
        """Respawn under the SAME id with a fresh incarnation (the crash
        plane's rejoin shape). Streams still frozen on the corpse — a
        restart racing detection — migrate now: the real system's rejoin
        purge aborts them the same way."""
        old = self.workers.get(wid)
        inc = (old.incarnation if old else 0) + 1
        leftovers = list(old.streams.values()) if old else []
        if old is not None:
            old.streams.clear()
        self._routable.discard(wid)
        self._spawn(ready_in=self.cfg.launch_delay_s, wid=wid,
                    incarnation=inc)
        for s in leftovers:
            self._migrate(s)
        self.events.append((self.now, "restart", wid))

    # -- Fleet protocol (ElasticController) ----------------------------------

    def ready_count(self, pool: str = "decode") -> int:
        return sum(1 for w in self.workers.values() if w.ready(self.now))

    def load_view(self, pool: str = "decode") -> Dict[int, float]:
        return {
            w.wid: float(sum(s.blocks for s in w.streams.values()))
            for w in self.workers.values()
            if w.ready(self.now)
        }

    async def launch(self, pool: str, n: int) -> None:
        for _ in range(n):
            self._spawn(ready_in=self.cfg.launch_delay_s)
        self.events.append((self.now, "launch", n))

    async def wait_ready(self, pool: str, want: int, deadline_s: float) -> int:
        """The /readyz gate: the WORLD keeps moving (arrivals, decode,
        reports, chaos) while the controller waits for replicas to warm."""
        deadline = self.now + deadline_s
        while self.now < deadline and self.ready_count(pool) < want:
            self.step(self.cfg.substep_s)
        return self.ready_count(pool)

    async def drain(self, pool: str, wid: int) -> Dict[str, Any]:
        return self._drain_sync(wid)

    def _drain_sync(self, wid: int) -> Dict[str, Any]:
        """Drain-with-handoff: flip the draining bit (force-published so
        the scheduler deflects NOW), live-hand every resident stream to a
        peer with its fold state carried VERBATIM (zero re-prefilled
        tokens), then deregister. The ladder's re-prefill rung only fires
        when no peer exists."""
        w = self.workers[wid]
        w.draining = True
        self.scheduler.update_load(self._snapshot(w))
        handoffs = 0
        fell_back = 0
        for s in list(w.streams.values()):
            del w.streams[s.rid]
            self._release_charge(s)
            # Peer ranking, the drain controller's own (not the router's):
            # least-loaded serving peer WITH admission capacity — the real
            # plane's peer walk ends at peers that refuse on capacity, so
            # the sim must enforce the same hard cap instead of piling a
            # retiring worker's whole pool onto one saturated adopter.
            peers = sorted(
                (len(p.streams), p.wid)
                for p in self.workers.values()
                if p.wid != wid and p.ready(self.now)
                and len(p.streams) < self.cfg.hard_cap
            )
            if not peers:
                # Every peer refused (or none serving): the re-prefill
                # migration rung. The tokens land at the re-dispatch; the
                # attribution is charged here (the stream is frozen
                # meanwhile, so the amount is exact).
                fell_back += s.isl + len(s.tokens)
                self._migrate(s)
                continue
            peer = self.workers[peers[0][1]]
            peer.streams[s.rid] = s
            s.worker = peer.wid
            s.prefill_until = self.now + self.cfg.handoff_pause_s
            s.handoffs += 1
            handoffs += 1
            self.handoff_streams += 1
        # Deregister: lease released, discovery DELETE — the tracker
        # forgets the worker (a drained exit must never read as a death).
        self._routable.discard(wid)
        self.tracker.drop(wid)
        self.scheduler.drop_worker((wid, 0))
        self.workers.pop(wid, None)
        self.retired.append(wid)
        self.events.append((self.now, "drain", wid))
        self.drain_reprefill_tokens += fell_back
        return {
            "handoffs": handoffs,
            "reprefill_tokens": fell_back,
        }

    # -- routing / migration -------------------------------------------------

    def _request_blocks(self, s: SimStream) -> int:
        return s.isl // self.cfg.block_size + 1

    def _route(self, s: SimStream) -> Optional[int]:
        candidates = [(wid, 0) for wid in sorted(self._routable)]
        if not candidates:
            return None
        chosen = self.scheduler.select_worker(
            self._request_blocks(s), OverlapScores(), candidates
        )
        if chosen is None:
            return None
        s.charged_worker = chosen[0]
        s.charged_blocks = self._request_blocks(s)
        s.report_gen = self.scheduler.report_generation(chosen)
        return chosen[0]

    def _release_charge(self, s: SimStream) -> None:
        if s.charged_worker is not None and s.charged_blocks:
            self.scheduler.complete_request(
                (s.charged_worker, 0), s.charged_blocks, s.report_gen
            )
        s.charged_worker = None
        s.charged_blocks = 0

    def _admit(self, s: SimStream, wid: int, *, reprefill: bool) -> None:
        w = self.workers[wid]
        w.streams[s.rid] = s
        s.worker = wid
        s.prefill_until = self.now + self.cfg.ttft_of(
            s.isl + (len(s.tokens) if reprefill else 0)
        )
        if reprefill:
            # Re-prefill reconstructs the fold state from the carried
            # tokens — a lost/duplicated token diverges every token after.
            state = _seed_state(s.rid)
            for tok in s.tokens:
                state = _fold(state, tok)
            s.state = state
            self.reprefill_tokens += s.isl + len(s.tokens)

    def _dispatch(self, s: SimStream) -> bool:
        dest = self._route(s)
        if dest is None:
            return False
        w = self.workers[dest]
        if w.draining or not w.alive or len(w.streams) >= self.cfg.hard_cap:
            # Typed refusal (draining/dead-but-undetected/saturated):
            # the stream bounces back to the backlog — the requeue rung.
            self._release_charge(s)
            self.requeues += 1
            return False
        self._admit(s, dest, reprefill=s.migrations > 0)
        return True

    def _migrate(self, s: SimStream) -> None:
        """Carried-token re-dispatch (the PR 7 migration shape): the
        stream keeps its streamed tokens; the next worker re-prefills
        prompt + carried and continues."""
        s.migrations += 1
        self.migrated_streams += 1
        s.worker = None
        self.backlog.appendleft(s)

    def _on_dead(self, wid: int, _inc: int) -> None:
        if wid not in self.killed:
            self.false_positive_deaths.append(wid)
            logger.error("liveness FALSE POSITIVE: worker %#x", wid)
        w = self.workers.get(wid)
        if w is not None and not w.alive:
            self.detection_latencies.append(
                self.now - max(
                    (t for t, kind, a in self.events
                     if kind == "kill" and a == wid),
                    default=self.now,
                )
            )
        # The single purge path + typed stream aborts → migration.
        self.scheduler.drop_worker((wid, 0))
        self._routable.discard(wid)
        if w is not None and not w.alive:
            for s in list(w.streams.values()):
                del w.streams[s.rid]
                self._release_charge(s)
                self._migrate(s)
        self.events.append((self.now, "dead", wid))

    # -- the world tick ------------------------------------------------------

    def step(self, dt: Optional[float] = None) -> None:
        dt = self.cfg.substep_s if dt is None else dt
        self.now += dt
        self._fire_chaos()
        self._registration_sweep()
        self._generate_arrivals(dt)
        self._drain_backlog()
        self._decode(dt)
        if self.now >= self._next_report:
            self._next_report = self.now + self.cfg.report_interval_s
            self._publish_reports()
        self.tracker.evaluate()

    def run(self, duration_s: float) -> None:
        end = self.now + duration_s
        while self.now < end:
            self.step(self.cfg.substep_s)

    def _fire_chaos(self) -> None:
        while (
            self._chaos_fired < len(self._chaos)
            and self._chaos[self._chaos_fired][0] <= self.now
        ):
            _t, kind, arg = self._chaos[self._chaos_fired]
            self._chaos_fired += 1
            if kind == "kill":
                wid = arg if arg is not None else self._pick_victim()
                if wid is not None:
                    self.kill(wid)
            elif kind == "restart":
                wid = arg
                if wid is None and self._last_killed:
                    wid = self._last_killed.pop(0)
                if wid is not None and wid in self.killed:
                    self.restart(wid)
            elif kind == "drain":
                wid = arg if arg is not None else self._pick_victim()
                if wid is not None:
                    self._drain_sync(wid)
            elif kind == "overload":
                duration, mult = arg
                self._overload_until = self.now + duration
                self._overload_mult = float(mult)
                self.events.append((self.now, "overload", arg))
            else:
                raise ValueError(f"unknown chaos kind {kind!r}")

    def _pick_victim(self) -> Optional[int]:
        live = sorted(
            w.wid for w in self.workers.values() if w.ready(self.now)
        )
        if len(live) <= 1:
            return None  # never leave the fleet empty
        return self.rng.choice(live)

    def _registration_sweep(self) -> None:
        for w in self.workers.values():
            if w.ready(self.now):
                self._routable.add(w.wid)

    def _generate_arrivals(self, dt: float) -> None:
        rate = self.rate_fn(self.now)
        if self.now < self._overload_until:
            rate *= self._overload_mult
        self._arrival_acc += rate * dt
        while self._arrival_acc >= 1.0:
            self._arrival_acc -= 1.0
            rid = f"r{self.arrivals}"
            self.arrivals += 1
            self._interval_arrivals += 1
            self.backlog.append(
                SimStream(
                    rid=rid, isl=self.cfg.isl, osl=self.cfg.osl,
                    arrived=self.now, state=_seed_state(rid),
                    block_size=self.cfg.block_size,
                )
            )

    def _drain_backlog(self) -> None:
        # FIFO head-of-line admission: one refusal stalls the queue for a
        # substep (a 429'd client honoring Retry-After) — a corpse
        # attracting placement stalls arrivals for exactly the detection
        # budget, then the purge unblocks the flood.
        while self.backlog:
            if not self._dispatch(self.backlog[0]):
                break
            self.backlog.popleft()

    def _decode(self, dt: float) -> None:
        for w in list(self.workers.values()):
            if not w.alive or self.now < w.ready_at:
                continue  # a corpse's streams freeze; a warming worker idles
            active = [
                s for s in w.streams.values() if self.now >= s.prefill_until
            ]
            if not active:
                continue
            itl = self.cfg.itl_of(len(w.streams))
            for s in active:
                if s.first_token_at is None:
                    s.first_token_at = s.prefill_until
                s.acc += dt / itl
                while s.acc >= 1.0 and len(s.tokens) < s.osl:
                    s.acc -= 1.0
                    tok = _token_of(s.state)
                    s.tokens.append(tok)
                    s.state = _fold(s.state, tok)
                if len(s.tokens) >= s.osl:
                    s.done_at = self.now
                    del w.streams[s.rid]
                    self._release_charge(s)
                    self.completed.append(s)
                    self._interval_done.append(s)

    def _snapshot(self, w: SimWorker) -> LoadSnapshot:
        return LoadSnapshot(
            worker_id=w.wid,
            active_blocks=sum(s.blocks for s in w.streams.values()),
            total_blocks=self.cfg.blocks_per_worker,
            active_seqs=len(w.streams),
            queue_depth=0,
            draining=w.draining,
            incarnation=w.incarnation,
        )

    def _publish_reports(self) -> None:
        for w in self.workers.values():
            if not w.alive or self.now < w.ready_at:
                continue  # silence: exactly what the liveness plane reads
            snap = self._snapshot(w)
            self.scheduler.update_load(snap)
            self.tracker.observe_report(w.wid, w.incarnation)

    # -- planner inputs ------------------------------------------------------

    def metrics_snapshot(self) -> MetricsSnapshot:
        """One adjustment interval's observed metrics (call once per
        planner step — the scrape-source role)."""
        done = self._interval_done
        self._interval_done = []
        arrivals = self._interval_arrivals
        self._interval_arrivals = 0
        dt = max(self.now - self._interval_started, 1e-9)
        self._interval_started = self.now
        snap = MetricsSnapshot(
            request_rate=arrivals / dt,
            mean_isl=float(
                statistics.fmean(s.isl for s in done) if done else 0.0
            ),
            mean_osl=float(
                statistics.fmean(s.osl for s in done) if done else 0.0
            ),
            p50_ttft_s=(
                statistics.median(s.first_token_at - s.arrived for s in done)
                if done else None
            ),
            p50_itl_s=(
                statistics.median(
                    (s.done_at - s.first_token_at) / max(s.osl - 1, 1)
                    for s in done
                )
                if done else None
            ),
        )
        return snap

    async def metrics_source(self) -> MetricsSnapshot:
        return self.metrics_snapshot()

    # -- soak assertions -----------------------------------------------------

    def in_flight(self) -> int:
        return len(self.backlog) + sum(
            len(w.streams) for w in self.workers.values()
        )

    def settle(self, max_s: float = 120.0) -> None:
        """Run with no new arrivals until every stream resolves."""
        rate_fn = self.rate_fn
        self.rate_fn = lambda _t: 0.0
        deadline = self.now + max_s
        try:
            while self.in_flight() > 0 and self.now < deadline:
                self.step(self.cfg.substep_s)
        finally:
            self.rate_fn = rate_fn

    def verify_streams(self) -> List[str]:
        """Token-exactness vs the never-disturbed oracle. Returns the
        problems (empty = zero lost streams, every one exact)."""
        problems = []
        if self.in_flight() > 0:
            problems.append(f"{self.in_flight()} streams never completed")
        if len(self.completed) != self.arrivals:
            problems.append(
                f"{self.arrivals} arrivals but {len(self.completed)} "
                "completions"
            )
        seen = set()
        for s in self.completed:
            if s.rid in seen:
                problems.append(f"{s.rid} completed twice")
            seen.add(s.rid)
            want = expected_tokens(s.rid, s.osl)
            if s.tokens != want:
                problems.append(
                    f"{s.rid} diverged from oracle after "
                    f"{s.migrations} migrations/{s.handoffs} handoffs"
                )
        return problems
