"""Planner service entrypoint.

Reference parity: components/src/dynamo/planner/planner_sla.py (the SLA
planner component: scrape metrics → predict → size pools → apply via a
connector). Modes:

  --connector virtual   write desired counts to the discovery plane
                        (tests / operator equivalent picks them up)
  --connector process   spawn/retire worker subprocesses directly
                        (single-host deployments; see process_connector.py)

Usage:
  python -m dynamo_tpu.planner --metrics-url http://127.0.0.1:8080/metrics \
      --profile profile.json --connector process \
      --decode-cmd "python -m dynamo_tpu.worker --model tiny"
"""

from __future__ import annotations

import argparse
import asyncio
import shlex
import sys

from dynamo_tpu import config
from dynamo_tpu.planner.connectors import VirtualConnector
from dynamo_tpu.planner.metrics_source import FrontendScrapeSource
from dynamo_tpu.planner.perf_interpolation import (
    DecodeInterpolator,
    PrefillInterpolator,
    load_profile,
)
from dynamo_tpu.planner.planner_core import Planner, PlannerConfig
from dynamo_tpu.planner.process_connector import ProcessConnector, RoleSpec
from dynamo_tpu.utils.logging import configure_logging, get_logger

logger = get_logger(__name__)


def _default_interpolators():
    """Conservative single-point fallbacks when no profile is given."""
    prefill = PrefillInterpolator([512.0], [0.2], [4000.0])
    decode = DecodeInterpolator([1.0, 8.0], [0.01, 0.03], [100.0, 500.0])
    return prefill, decode


async def main() -> None:
    parser = argparse.ArgumentParser("dynamo-tpu planner")
    parser.add_argument("--metrics-url", action="append", required=True,
                        help="frontend /metrics URL (repeatable)")
    parser.add_argument("--model", default=None, help="restrict to one model")
    parser.add_argument("--profile", default=None,
                        help="profiler sweep JSON (see dynamo_tpu.profiler)")
    parser.add_argument("--namespace", default=config.NAMESPACE.get())
    parser.add_argument("--adjustment-interval", type=float, default=30.0)
    parser.add_argument("--ttft-target", type=float, default=0.5)
    parser.add_argument("--itl-target", type=float, default=0.02)
    parser.add_argument("--min-replicas", type=int, default=1)
    parser.add_argument("--max-replicas", type=int, default=8)
    parser.add_argument("--total-chip-budget", type=int, default=8)
    parser.add_argument("--predictor", default="moving-average")
    parser.add_argument("--no-disagg", action="store_true",
                        help="aggregated deployment: size only the decode pool")
    parser.add_argument("--feedback-decay", type=float, default=0.4,
                        help="correction-factor EWMA weight folding observed/"
                        "predicted TTFT+ITL ratios into the profile table "
                        "(docs/design_docs/elasticity.md); 0 disables "
                        "feedback and trusts the table forever")
    parser.add_argument("--connector", choices=("virtual", "process"),
                        default="virtual")
    parser.add_argument("--decode-cmd", default=None,
                        help="worker launch command (process connector)")
    parser.add_argument("--prefill-cmd", default=None)
    args = parser.parse_args()

    configure_logging()
    if args.profile:
        prefill_interp, decode_interp = load_profile(args.profile)
    else:
        logger.warning("no --profile given; using conservative defaults")
        prefill_interp, decode_interp = _default_interpolators()

    connector: object
    if args.connector == "process":
        if not args.decode_cmd:
            parser.error("--connector process requires --decode-cmd")
        roles = {"decode": RoleSpec(command=shlex.split(args.decode_cmd))}
        if args.prefill_cmd:
            roles["prefill"] = RoleSpec(command=shlex.split(args.prefill_cmd))
        connector = ProcessConnector(roles, stdout=sys.stderr)
    else:
        from dynamo_tpu.runtime.distributed import DistributedRuntime

        runtime = DistributedRuntime.from_settings()
        connector = VirtualConnector(runtime.discovery, args.namespace)

    from dynamo_tpu.planner.feedback import FeedbackConfig

    planner = Planner(
        PlannerConfig(
            adjustment_interval_s=args.adjustment_interval,
            ttft_target_s=args.ttft_target,
            itl_target_s=args.itl_target,
            predictor=args.predictor,
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas,
            total_chip_budget=args.total_chip_budget,
            feedback=FeedbackConfig(decay=args.feedback_decay),
        ),
        prefill_interp,
        decode_interp,
        connector,
        FrontendScrapeSource(args.metrics_url, model=args.model),
        disagg=not args.no_disagg,
    )
    planner.start()
    print("planner running", flush=True)
    try:
        await asyncio.Event().wait()
    finally:
        await planner.stop()
        if isinstance(connector, ProcessConnector):
            await connector.close()


if __name__ == "__main__":
    asyncio.run(main())
