"""Scaling connectors: apply a ReplicaPlan to the world.

Reference parity: components/src/dynamo/planner/{kubernetes_connector.py,
virtual_connector.py}. The virtual connector publishes the desired counts
to the discovery plane (key ``planner/{namespace}/desired``) where tests,
a process supervisor, or the k8s operator equivalent picks them up — the
same decoupling the reference gets from patching DynamoGraphDeployment
replicas and letting the operator reconcile.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def planner_key(namespace: str) -> str:
    return f"planner/{namespace}/desired"


class ScalingAdapterConnector:
    """Apply a ReplicaPlan by patching ScalingAdapter CRs — the planner
    never touches pods or GraphDeployments directly; the operator's adapter
    reconciler is the single writer of service replicas.

    Reference parity: components/src/dynamo/planner/kubernetes_connector.py
    (planner patches a CR, operator reconciles) +
    deploy/operator/api/v1alpha1/dynamographdeploymentscalingadapter_types.go
    (the adapter intermediary that serializes autoscaler writes)."""

    def __init__(
        self,
        client: Any,  # deploy.k8s_client.KubeClient
        deployment: str,  # target GraphDeployment name
        *,
        k8s_namespace: str = "default",
        prefill_service: str = "prefill",
        decode_service: str = "decode",
    ) -> None:
        self.client = client
        self.deployment = deployment
        self.k8s_namespace = k8s_namespace
        self.prefill_service = prefill_service
        self.decode_service = decode_service
        self.applied: Optional[Dict[str, int]] = None

    def _adapter_name(self, service: str) -> str:
        return f"{self.deployment}-{service}"

    async def _ensure_and_patch(self, service: str, replicas: int) -> None:
        from dynamo_tpu.deploy.k8s_operator import (
            GROUP, SA_PLURAL, VERSION,
        )
        from dynamo_tpu.deploy.k8s_client import KubeApiError

        name = self._adapter_name(service)
        body = {
            "apiVersion": f"{GROUP}/{VERSION}",
            "kind": "DynamoTpuScalingAdapter",
            "metadata": {"name": name},
            "spec": {
                "replicas": int(replicas),
                "dgdRef": {
                    "name": self.deployment,
                    "serviceName": service,
                },
            },
        }
        patch_body = {"spec": {"replicas": int(replicas)}}
        try:
            await self.client.patch(
                GROUP, VERSION, self.k8s_namespace, SA_PLURAL, name,
                patch_body,
            )
        except KubeApiError as exc:
            if exc.status != 404:
                raise
            try:
                await self.client.create(
                    GROUP, VERSION, self.k8s_namespace, SA_PLURAL, body
                )
            except KubeApiError as cexc:
                if cexc.status != 409:
                    raise
                # Lost the create race (another planner replica / operator
                # reconcile landed between our 404 and the create): the
                # adapter now exists, so 409 means "exists" — retry the
                # patch once instead of killing the whole plan apply.
                logger.info(
                    "adapter %s created concurrently; retrying patch", name
                )
                await self.client.patch(
                    GROUP, VERSION, self.k8s_namespace, SA_PLURAL, name,
                    patch_body,
                )

    async def apply(self, plan) -> None:
        if self.prefill_service == self.decode_service:
            # Aggregated single-pool deployment: one adapter serves both
            # roles — size it for the LARGER demand instead of letting the
            # second write silently clobber the first.
            await self._ensure_and_patch(
                self.decode_service, max(int(plan.prefill), int(plan.decode))
            )
        else:
            await self._ensure_and_patch(self.prefill_service, plan.prefill)
            await self._ensure_and_patch(self.decode_service, plan.decode)
        self.applied = {
            "prefill": int(plan.prefill), "decode": int(plan.decode)
        }
        logger.info(
            "planner → adapters %s: prefill=%d decode=%d (%s)",
            self.deployment, plan.prefill, plan.decode, plan.reason,
        )


class VirtualConnector:
    def __init__(self, discovery: Any, namespace: str) -> None:
        self.discovery = discovery
        self.namespace = namespace
        self.applied: Optional[Dict[str, int]] = None

    async def apply(self, plan) -> None:
        doc = {
            "prefill": int(plan.prefill),
            "decode": int(plan.decode),
            "reason": plan.reason,
            "ts": time.time(),
        }
        await self.discovery.put(planner_key(self.namespace), doc)
        self.applied = doc

    async def read_desired(self) -> Optional[Dict[str, Any]]:
        return await self.discovery.get(planner_key(self.namespace))
