"""Scaling connectors: apply a ReplicaPlan to the world.

Reference parity: components/src/dynamo/planner/{kubernetes_connector.py,
virtual_connector.py}. The virtual connector publishes the desired counts
to the discovery plane (key ``planner/{namespace}/desired``) where tests,
a process supervisor, or the k8s operator equivalent picks them up — the
same decoupling the reference gets from patching DynamoGraphDeployment
replicas and letting the operator reconcile.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def planner_key(namespace: str) -> str:
    return f"planner/{namespace}/desired"


class VirtualConnector:
    def __init__(self, discovery: Any, namespace: str) -> None:
        self.discovery = discovery
        self.namespace = namespace
        self.applied: Optional[Dict[str, int]] = None

    async def apply(self, plan) -> None:
        doc = {
            "prefill": int(plan.prefill),
            "decode": int(plan.decode),
            "reason": plan.reason,
            "ts": time.time(),
        }
        await self.discovery.put(planner_key(self.namespace), doc)
        self.applied = doc

    async def read_desired(self) -> Optional[Dict[str, Any]]:
        return await self.discovery.get(planner_key(self.namespace))
