"""Elastic actuation: a plan-transition state machine that scales the
fleet THROUGH the robustness planes instead of around them.

The planner's sizing loop (planner_core.py) says how many replicas each
pool should have; this module is the connector that makes it true without
dropping or re-prefilling a single stream:

  * **Scale-down is a drain, never a kill.** Victims are selected
    (least-loaded first — the cheapest live handoffs) and retired through
    the PR 9 drain plane: ``POST /drain`` / SIGTERM → live handoff of
    every in-flight decode to a peer over the int8 wire → zero
    re-prefilled tokens, bit-identical continuations. Spot preemption
    (:meth:`ElasticController.preempt`) rides the exact same path — the
    only difference is who picked the victim.
  * **Scale-up counts nothing it can't route to.** A launched replica is
    only counted once the fleet reports it ready — the worker main's
    ``/readyz`` gate, which stays 503 through engine start AND the warm
    KV-checkpoint restore — so a plan never "converges" onto replicas
    that would 503 the router.
  * **Hysteresis so oscillating load can't flap the fleet.** A scale-up
    must persist ``scale_up_after`` consecutive intervals and a
    scale-down ``scale_down_after`` (down is slower: killing warm caches
    on a transient dip costs more than riding it out), and every
    actuation is followed by ``cooldown_intervals`` of enforced holds —
    suppressed changes are counted (``dynamo_tpu_planner_holds_total``),
    not silently dropped.

State machine (the ``dynamo_tpu_planner_state`` gauge)::

    steady ──want>have for scale_up_after──▶ scaling_up ──all /readyz──▶ converged
       ▲  └─want<have for scale_down_after─▶ scaling_down ──all drained──┘   │
       └──────────────────── cooldown_intervals of holds ────────────────────┘

The controller drives any fleet exposing the small :class:`Fleet`
protocol; ``planner/simfleet.py`` implements it for the fleet-scale soak,
and a process/k8s deployment maps it onto the PR 9 surfaces (SIGTERM or
``POST /drain`` for ``drain``, ``GET /readyz`` polling for
``wait_ready``).
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Protocol

from dynamo_tpu.planner.feedback import PlannerMetrics
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# Plan-transition states (also the dynamo_tpu_planner_state gauge).
STEADY, SCALING_UP, SCALING_DOWN, CONVERGED = 0, 1, 2, 3
_STATE_NAMES = {
    STEADY: "steady",
    SCALING_UP: "scaling_up",
    SCALING_DOWN: "scaling_down",
    CONVERGED: "converged",
}


class Fleet(Protocol):
    """What the controller needs from the world. One instance may serve
    several pools (``"prefill"`` / ``"decode"``)."""

    def ready_count(self, pool: str) -> int:
        """Replicas that are launched AND ready (``/readyz`` green —
        engine up, warm restore done, not draining)."""

    def load_view(self, pool: str) -> Dict[int, float]:
        """worker id → load signal (active KV blocks / streams). Victim
        selection retires the least-loaded first."""

    async def launch(self, pool: str, n: int) -> None:
        """Start ``n`` replicas; they become ready later (wait_ready)."""

    async def wait_ready(self, pool: str, want: int, deadline_s: float) -> int:
        """Block until ``ready_count(pool) >= want`` or the deadline;
        returns the final ready count."""

    async def drain(self, pool: str, worker_id: int) -> Dict[str, Any]:
        """Retire one worker through the drain plane (live handoff of its
        in-flight streams, then exit). Returns drain stats (at least
        ``handoffs`` and ``reprefill_tokens``)."""


@dataclass
class ElasticConfig:
    # Consecutive intervals a direction must persist before actuating.
    # Scale-down is deliberately slower: a transient dip that kills warm
    # caches costs more than riding it out.
    scale_up_after: int = 1
    scale_down_after: int = 3
    # Enforced hold intervals after any completed actuation.
    cooldown_intervals: int = 2
    # Bound on one actuation (launch→ready or drain-all) — a stuck
    # replica or a wedged drain must not freeze the control loop forever.
    actuation_deadline_s: float = 120.0
    # Largest single scale-down step (fraction of the pool, min 1): even a
    # sustained plan collapse retires the fleet in bounded bites so the
    # survivors absorb each wave of handoffs before the next.
    max_down_fraction: float = 0.5
    # Intervals a launched-but-never-ready replica blocks re-launching
    # before the controller gives up on it (crashed pre-ready).
    pending_stale_after: int = 5

    def __post_init__(self) -> None:
        if self.scale_up_after < 1 or self.scale_down_after < 1:
            raise ValueError("hysteresis streaks must be >= 1")
        if not 0.0 < self.max_down_fraction <= 1.0:
            raise ValueError("max_down_fraction must be in (0, 1]")


@dataclass
class _PoolTrack:
    up_streak: int = 0
    down_streak: int = 0
    cooldown: int = 0
    # Per-pool plan state: the controller-global state (the gauge, the
    # feedback gate) is derived from all pools — a steady prefill pool
    # must never mask a decode pool's in-flight actuation.
    state: int = STEADY
    # Launched-but-not-yet-ready replicas from a previous actuation whose
    # warm-up outlived the actuation deadline: still coming, so a new
    # scale-up must not launch them AGAIN. Forgotten after
    # ``pending_stale_after`` intervals without the ready count reaching
    # the want (a launch that died pre-ready must not block re-launching
    # forever).
    pending: int = 0
    pending_intervals: int = 0


class ElasticController:
    """Planner connector executing ReplicaPlans through the drain/crash
    planes. Call-compatible with the other connectors (``await
    apply(plan)``), so ``Planner`` needs no special wiring."""

    def __init__(
        self,
        fleet: Fleet,
        *,
        config: Optional[ElasticConfig] = None,
        metrics: Optional[PlannerMetrics] = None,
        disagg: bool = True,
    ) -> None:
        from dynamo_tpu.runtime.device_observe import FlightRecorder

        self.fleet = fleet
        self.config = config or ElasticConfig()
        self.metrics = metrics if metrics is not None else PlannerMetrics()
        self.disagg = disagg
        self.state = STEADY
        self.metrics.state.set(STEADY)
        self._tracks: Dict[str, _PoolTrack] = {}
        # Actuation history for post-mortems (DYN005 owner "planner";
        # single writer: the planner's event loop).
        self.flight = FlightRecorder("planner", capacity=256)
        # Host-side mirrors (tests/bench read these without a scrape).
        self.scale_ups = 0
        self.scale_downs = 0
        self.preemptions = 0
        self.holds = 0
        self.drained_workers: List[int] = []
        self.reprefill_tokens_from_scaling = 0
        self.applied: Optional[Dict[str, int]] = None

    # -- surface -------------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        return {
            "state": _STATE_NAMES[self.state],
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "preemptions": self.preemptions,
            "holds": self.holds,
            "drained_workers": list(self.drained_workers),
            "reprefill_tokens_from_scaling": self.reprefill_tokens_from_scaling,
            "applied": self.applied,
        }

    def register_metrics(self, server: Any) -> None:
        self.metrics.register(server)  # idempotent when shared with Planner
        server.register_flight(self.flight.name, self.flight.snapshot)

    def feedback_stable(self) -> bool:
        """Gate for the planner's correction-factor folding: observations
        made while an actuation is in flight — or completed within the
        still-running cooldown — describe a DIFFERENT fleet size than the
        one the planner would charge them against, and folding them
        teaches phantom slowness. Only fully-steady intervals fold: every
        pool back in STEADY, i.e. at least cooldown_intervals past its
        last actuation (completions from the transition window have
        drained by then)."""
        return self.state == STEADY

    def _set_pool_state(self, track: _PoolTrack, state: int) -> None:
        """Per-pool transition; the global gauge is the most-active pool
        (scaling_down > scaling_up > converged > steady), so one pool
        going quiet can never mask another's in-flight actuation."""
        track.state = state
        tracks = self._tracks.values()
        for derived in (SCALING_DOWN, SCALING_UP, CONVERGED):
            if any(t.state == derived for t in tracks):
                break
        else:
            derived = STEADY
        if derived == self.state:
            return
        self.state = derived
        self.metrics.state.set(derived)
        self.metrics.transitions.inc(to=_STATE_NAMES[derived])
        self.flight.record("state", to=_STATE_NAMES[derived])

    # -- connector interface -------------------------------------------------

    async def apply(self, plan: Any) -> None:
        """One adjustment interval's actuation decision. Hysteresis and
        cooldown are evaluated per pool; at most one pool direction
        actuates per call (the next interval picks up the rest) so the
        fleet changes in observable steps."""
        targets = {"decode": int(plan.decode)}
        if self.disagg and int(plan.prefill) > 0:
            targets["prefill"] = int(plan.prefill)
        for pool, want in targets.items():
            await self._reconcile(pool, want)
        self.applied = {
            pool: self.fleet.ready_count(pool) for pool in targets
        }

    async def _reconcile(self, pool: str, want: int) -> bool:
        cfg = self.config
        track = self._tracks.setdefault(pool, _PoolTrack())
        have = self.fleet.ready_count(pool)
        if track.pending > 0:
            # Replicas from a previous actuation still warming: don't let
            # a new actuation double-launch them, but don't let a corpse
            # block re-launching forever either.
            track.pending = max(min(track.pending, want - have), 0)
            track.pending_intervals += 1
            if track.pending_intervals > cfg.pending_stale_after:
                self.flight.record(
                    "pending_forgotten", pool=pool, pending=track.pending
                )
                track.pending = 0
            if track.pending == 0:
                track.pending_intervals = 0
                self.metrics.scale_up_pending.set(0, pool=pool)
        if want > have:
            track.up_streak += 1
            track.down_streak = 0
        elif want < have:
            track.down_streak += 1
            track.up_streak = 0
        else:
            track.up_streak = track.down_streak = 0
            if track.state in (SCALING_UP, SCALING_DOWN):
                # A partial actuation finished catching up (pending
                # replicas went ready / stragglers drained) on its own.
                self._set_pool_state(track, CONVERGED)
            if track.cooldown > 0:
                track.cooldown -= 1
            if track.state == CONVERGED and track.cooldown == 0:
                self._set_pool_state(track, STEADY)
            return False
        if track.cooldown > 0:
            track.cooldown -= 1
            self._hold(pool, want, have, "cooldown")
            return False
        if want > have:
            if track.up_streak < cfg.scale_up_after:
                self._hold(
                    pool, want, have,
                    f"streak {track.up_streak}/{cfg.scale_up_after}",
                )
                return False
            await self._scale_up(pool, want, have, track)
            return True
        if track.down_streak < cfg.scale_down_after:
            self._hold(
                pool, want, have,
                f"streak {track.down_streak}/{cfg.scale_down_after}",
            )
            return False
        await self._scale_down(pool, want, have, track)
        return True

    def _hold(self, pool: str, want: int, have: int, why: str) -> None:
        self.holds += 1
        self.metrics.holds.inc()
        self.flight.record("hold", pool=pool, want=want, have=have, why=why)

    async def _scale_up(
        self, pool: str, want: int, have: int, track: _PoolTrack
    ) -> None:
        cfg = self.config
        # Previously-launched still-warming replicas count against the
        # shortfall: launching them again would overshoot the fleet and
        # feed the overshoot straight into a scale-down's drain churn.
        n = max(want - have - track.pending, 0)
        self._set_pool_state(track, SCALING_UP)
        self.flight.record(
            "scale_up", pool=pool, launching=n, have=have,
            pending=track.pending,
        )
        self.metrics.scale_up_pending.set(n + track.pending, pool=pool)
        launched = True
        try:
            if n > 0:
                await self.fleet.launch(pool, n)
        except Exception:
            # A failed launch call left the replicas UNLAUNCHED: they
            # must not be recorded as pending, or the next intervals
            # would launch n=0 and stall the scale-up on phantoms.
            logger.exception("launch of %d %s replicas failed", n, pool)
            launched = False
        try:
            # A replica only counts once /readyz (warm restore included)
            # goes green — never route a plan at a 503ing worker.
            ready = await self.fleet.wait_ready(
                pool, want, cfg.actuation_deadline_s
            )
        except Exception:
            logger.exception("scale-up of %s to %d failed", pool, want)
            ready = self.fleet.ready_count(pool)
        still_pending = max(want - ready, 0) if launched else max(
            want - ready - n, 0
        )
        if still_pending != track.pending:
            track.pending = still_pending
            track.pending_intervals = 0
        self.metrics.scale_up_pending.set(still_pending, pool=pool)
        self.scale_ups += 1
        track.up_streak = 0
        track.cooldown = cfg.cooldown_intervals
        if ready >= want:
            self._set_pool_state(track, CONVERGED)
            self.flight.record("converged", pool=pool, ready=ready)
        else:
            # Partial: stay in scaling_up for the gauge; the next interval
            # re-evaluates against the actual ready count.
            self.flight.record(
                "scale_up_partial", pool=pool, ready=ready, want=want
            )

    async def _scale_down(
        self, pool: str, want: int, have: int, track: _PoolTrack
    ) -> None:
        cfg = self.config
        step_cap = max(int(have * cfg.max_down_fraction), 1)
        n = min(have - want, step_cap)
        victims = self.select_victims(pool, n)
        self._set_pool_state(track, SCALING_DOWN)
        self.flight.record(
            "scale_down", pool=pool, retiring=len(victims), have=have,
            want=want,
        )
        drained = 0
        for wid in victims:
            ok = await self._drain_one(pool, wid, mode="planned")
            drained += 1 if ok else 0
        self.scale_downs += 1
        track.down_streak = 0
        track.cooldown = cfg.cooldown_intervals
        if drained == len(victims) and self.fleet.ready_count(pool) <= want:
            self._set_pool_state(track, CONVERGED)
            self.flight.record(
                "converged", pool=pool, ready=self.fleet.ready_count(pool)
            )

    async def _drain_one(self, pool: str, wid: int, *, mode: str) -> bool:
        cfg = self.config
        try:
            stats = await asyncio.wait_for(
                self.fleet.drain(pool, wid),
                timeout=cfg.actuation_deadline_s,
            )
        except Exception as exc:
            # The drain plane's own deadline ladder (handoff → re-prefill
            # → requeue) bounds what a failed drain costs the streams; the
            # controller only loses the capacity accounting for one
            # interval.
            logger.exception("drain of %s worker %#x failed", pool, wid)
            self.flight.record(
                "drain_error", pool=pool, worker=wid,
                error=f"{type(exc).__name__}: {exc}",
            )
            return False
        self.metrics.scale_down_drains.inc(mode=mode)
        self.drained_workers.append(wid)
        self.reprefill_tokens_from_scaling += int(
            stats.get("reprefill_tokens", 0) or 0
        )
        self.flight.record(
            "drained", pool=pool, worker=wid, mode=mode,
            handoffs=stats.get("handoffs"),
            reprefill_tokens=stats.get("reprefill_tokens"),
        )
        return True

    # -- spot preemption -----------------------------------------------------

    async def preempt(self, pool: str, worker_id: int) -> bool:
        """Spot/maintenance reclaim of a NAMED worker: no hysteresis (the
        machine is going away on the provider's clock, not ours), same
        drain-with-handoff path, counted under mode=preemption. The next
        planner interval re-sizes the pool around the loss."""
        self.preemptions += 1
        self.flight.record("preempt", pool=pool, worker=worker_id)
        return await self._drain_one(pool, worker_id, mode="preemption")

    # -- victim policy -------------------------------------------------------

    def select_victims(self, pool: str, n: int) -> List[int]:
        """Least-loaded first: fewer resident streams means fewer (and
        cheaper) live handoffs per retirement. Ties break on the HIGHER
        worker id — newest-ish first, deterministic — matching the
        process connector's newest-first retirement instinct."""
        view = self.fleet.load_view(pool)
        ranked = sorted(view.items(), key=lambda kv: (kv[1], -kv[0]))
        return [wid for wid, _load in ranked[:n]]
