"""Planner dry-run simulator: replay a load trace through the sizing math.

Reference parity: components/src/dynamo/planner/utils/dryrun.py — before
deploying an autoscaling policy, replay a (synthetic or recorded) load
trace against the planner's predictors + interpolators and report what it
WOULD have done: the replica timeline, scale events, peak chip usage, and
predicted SLA violations. No connectors, no clock — pure arithmetic.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from dynamo_tpu.planner.perf_interpolation import (
    DecodeInterpolator,
    PrefillInterpolator,
)
from dynamo_tpu.planner.planner_core import (
    MetricsSnapshot,
    Planner,
    PlannerConfig,
    ReplicaPlan,
)


@dataclass
class TracePoint:
    t: float  # seconds since trace start
    request_rate: float  # requests/sec
    mean_isl: float
    mean_osl: float


def synth_trace(
    kind: str = "ramp",
    *,
    duration_s: float = 600.0,
    interval_s: float = 30.0,
    base_rate: float = 1.0,
    peak_rate: float = 10.0,
    isl: float = 512.0,
    osl: float = 128.0,
) -> List[TracePoint]:
    """Synthetic load shapes: ramp (linear up), step (sudden jump at the
    midpoint), sine (one full period), spike (peak for one interval)."""
    points = []
    n = max(int(duration_s / interval_s), 1)
    for i in range(n):
        t = i * interval_s
        frac = i / max(n - 1, 1)
        if kind == "ramp":
            rate = base_rate + (peak_rate - base_rate) * frac
        elif kind == "step":
            rate = base_rate if frac < 0.5 else peak_rate
        elif kind == "sine":
            rate = base_rate + (peak_rate - base_rate) * 0.5 * (
                1 - math.cos(2 * math.pi * frac)
            )
        elif kind == "spike":
            rate = peak_rate if i == n // 2 else base_rate
        else:
            raise ValueError(f"unknown trace kind {kind!r}")
        points.append(TracePoint(t=t, request_rate=rate, mean_isl=isl, mean_osl=osl))
    return points


@dataclass
class ScaleEvent:
    t: float
    prefill: int
    decode: int
    reason: str


@dataclass
class DryRunReport:
    timeline: List[ScaleEvent] = field(default_factory=list)
    scale_events: int = 0  # plan changes (what a connector would execute)
    peak_chips: int = 0
    peak_prefill: int = 0
    peak_decode: int = 0
    ttft_violations: int = 0  # intervals where the model can't meet TTFT
    final_plan: Optional[ReplicaPlan] = None

    def summary(self) -> str:
        return (
            f"{self.scale_events} scale events, peak {self.peak_prefill}P/"
            f"{self.peak_decode}D ({self.peak_chips} chips), "
            f"{self.ttft_violations} TTFT-infeasible intervals"
        )


class DryRunner:
    """Feed a trace through the real Planner sizing math, synchronously."""

    def __init__(
        self,
        config: PlannerConfig,
        prefill_interp: PrefillInterpolator,
        decode_interp: DecodeInterpolator,
        *,
        disagg: bool = True,
    ) -> None:
        self._planner = Planner(
            config,
            prefill_interp,
            decode_interp,
            connector=None,
            metrics_source=None,
            disagg=disagg,
        )
        self.config = config

    def run(self, trace: Sequence[TracePoint]) -> DryRunReport:
        planner = self._planner
        cfg = self.config
        report = DryRunReport()
        last: Optional[ReplicaPlan] = None
        for pt in trace:
            snap = MetricsSnapshot(
                request_rate=pt.request_rate,
                mean_isl=pt.mean_isl,
                mean_osl=pt.mean_osl,
            )
            planner.rate_pred.add_data_point(snap.request_rate)
            planner.isl_pred.add_data_point(snap.mean_isl)
            planner.osl_pred.add_data_point(snap.mean_osl)
            plan = planner.compute_plan()
            if plan is None:
                continue
            if planner.prefill_interp.interpolate_ttft(pt.mean_isl) > cfg.ttft_target_s:
                report.ttft_violations += 1
            chips = (
                plan.prefill * cfg.chips_per_prefill_worker
                + plan.decode * cfg.chips_per_decode_worker
            )
            report.peak_chips = max(report.peak_chips, chips)
            report.peak_prefill = max(report.peak_prefill, plan.prefill)
            report.peak_decode = max(report.peak_decode, plan.decode)
            if last is None or (plan.prefill, plan.decode) != (last.prefill, last.decode):
                report.scale_events += 1
                report.timeline.append(
                    ScaleEvent(
                        t=pt.t, prefill=plan.prefill, decode=plan.decode,
                        reason=plan.reason,
                    )
                )
            last = plan
        report.final_plan = last
        return report
