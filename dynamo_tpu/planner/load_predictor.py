"""Load predictors: forecast the next interval's request rate / ISL / OSL.

Reference parity: components/src/dynamo/planner/utils/load_predictor.py
(:97 ConstantPredictor, ARIMA :150, Prophet :230, Kalman :320). ARIMA/Prophet
pull heavyweight deps the environment doesn't ship, so the trend-capable
middle ground is a double-exponential (Holt) moving average; the Kalman
filter is implemented directly (it's 20 lines of numpy).
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Optional

import numpy as np


class BasePredictor:
    def __init__(self, window: int = 50) -> None:
        self.window = window
        self.data: Deque[float] = deque(maxlen=window)

    def add_data_point(self, value: float) -> None:
        if value is not None and not np.isnan(value):
            self.data.append(float(value))

    def get_last(self) -> Optional[float]:
        return self.data[-1] if self.data else None

    def predict_next(self) -> Optional[float]:
        raise NotImplementedError


class ConstantPredictor(BasePredictor):
    """Next = last observed (ref: load_predictor.py:97)."""

    def predict_next(self) -> Optional[float]:
        return self.get_last()


class MovingAveragePredictor(BasePredictor):
    """Holt double-exponential smoothing: tracks level + trend — the
    dependency-free stand-in for the reference's ARIMA predictor."""

    def __init__(self, window: int = 50, alpha: float = 0.5, beta: float = 0.2) -> None:
        super().__init__(window)
        self.alpha = alpha
        self.beta = beta
        self._level: Optional[float] = None
        self._trend: float = 0.0

    def add_data_point(self, value: float) -> None:
        super().add_data_point(value)
        v = float(value)
        if self._level is None:
            self._level = v
            return
        prev_level = self._level
        self._level = self.alpha * v + (1 - self.alpha) * (self._level + self._trend)
        self._trend = self.beta * (self._level - prev_level) + (1 - self.beta) * self._trend

    def predict_next(self) -> Optional[float]:
        if self._level is None:
            return None
        return max(self._level + self._trend, 0.0)


class KalmanPredictor(BasePredictor):
    """1-D constant-velocity Kalman filter over the load series
    (ref: load_predictor.py:320)."""

    def __init__(self, window: int = 50, process_var: float = 1.0, obs_var: float = 10.0) -> None:
        super().__init__(window)
        self.q = process_var
        self.r = obs_var
        self.x = np.zeros(2)  # [level, velocity]
        self.P = np.eye(2) * 100.0
        self._initialized = False

    def add_data_point(self, value: float) -> None:
        super().add_data_point(value)
        z = float(value)
        if not self._initialized:
            self.x = np.array([z, 0.0])
            self._initialized = True
            return
        F = np.array([[1.0, 1.0], [0.0, 1.0]])
        H = np.array([[1.0, 0.0]])
        Q = np.eye(2) * self.q
        # predict
        self.x = F @ self.x
        self.P = F @ self.P @ F.T + Q
        # update
        y = z - (H @ self.x)[0]
        S = (H @ self.P @ H.T)[0, 0] + self.r
        K = (self.P @ H.T)[:, 0] / S
        self.x = self.x + K * y
        self.P = (np.eye(2) - np.outer(K, H[0])) @ self.P

    def predict_next(self) -> Optional[float]:
        if not self._initialized:
            return None
        return max(self.x[0] + self.x[1], 0.0)


_PREDICTORS = {
    "constant": ConstantPredictor,
    "moving-average": MovingAveragePredictor,
    "arima": MovingAveragePredictor,  # reference name → Holt stand-in
    "kalman": KalmanPredictor,
}


def make_predictor(kind: str, **kwargs) -> BasePredictor:
    try:
        return _PREDICTORS[kind](**kwargs)
    except KeyError:
        raise ValueError(
            f"unknown predictor {kind!r}; choose from {sorted(_PREDICTORS)}"
        ) from None
