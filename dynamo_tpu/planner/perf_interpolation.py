"""Performance interpolation over profiler sweep data.

Reference parity: components/src/dynamo/planner/utils/perf_interpolation.py
(PrefillInterpolator :37 — TTFT(isl) and prefill throughput(isl);
DecodeInterpolator :102 — ITL(context, concurrency) and per-seq decode
throughput). Sweep points come from the profiler (dynamo_tpu.profiler) as a
JSON dict; interpolation is piecewise-linear with edge clamping (numpy
interp / bilinear on the sorted grid).
"""

from __future__ import annotations

import json
from typing import Dict, List, Sequence

import numpy as np


class PrefillInterpolator:
    """TTFT and throughput as a function of input sequence length."""

    def __init__(self, isl: Sequence[float], ttft_s: Sequence[float],
                 tokens_per_s: Sequence[float]) -> None:
        order = np.argsort(isl)
        self.isl = np.asarray(isl, dtype=float)[order]
        self.ttft_s = np.asarray(ttft_s, dtype=float)[order]
        self.tokens_per_s = np.asarray(tokens_per_s, dtype=float)[order]

    def interpolate_ttft(self, isl: float) -> float:
        return float(np.interp(isl, self.isl, self.ttft_s))

    def interpolate_throughput(self, isl: float) -> float:
        """Prefill tokens/sec/worker at this ISL."""
        return float(np.interp(isl, self.isl, self.tokens_per_s))

    @classmethod
    def from_points(cls, points: List[Dict[str, float]]) -> "PrefillInterpolator":
        return cls(
            [p["isl"] for p in points],
            [p["ttft_s"] for p in points],
            [p["tokens_per_s"] for p in points],
        )


class DecodeInterpolator:
    """ITL and per-sequence decode throughput vs batch concurrency.

    The reference interpolates over (context_length, active_kv_usage); the
    dominant axis for a fixed-shape deployment is concurrency, so the sweep
    is (concurrency → itl_s, tokens_per_s_total)."""

    def __init__(self, concurrency: Sequence[float], itl_s: Sequence[float],
                 tokens_per_s: Sequence[float]) -> None:
        order = np.argsort(concurrency)
        self.concurrency = np.asarray(concurrency, dtype=float)[order]
        self.itl_s = np.asarray(itl_s, dtype=float)[order]
        self.tokens_per_s = np.asarray(tokens_per_s, dtype=float)[order]

    def interpolate_itl(self, concurrency: float) -> float:
        return float(np.interp(concurrency, self.concurrency, self.itl_s))

    def interpolate_throughput(self, concurrency: float) -> float:
        """Total decode tokens/sec/worker at this concurrency."""
        return float(np.interp(concurrency, self.concurrency, self.tokens_per_s))

    def max_concurrency_for_itl(self, itl_target_s: float) -> float:
        """Highest concurrency whose interpolated ITL still meets the SLA."""
        ok = self.itl_s <= itl_target_s
        if not ok.any():
            return float(self.concurrency[0])  # nothing meets it; be minimal
        if ok.all():
            return float(self.concurrency[-1])
        # Find the crossing between the last ok point and the first bad one.
        idx = int(np.argmax(~ok)) - 1
        lo_c, hi_c = self.concurrency[idx], self.concurrency[idx + 1]
        lo_i, hi_i = self.itl_s[idx], self.itl_s[idx + 1]
        if hi_i == lo_i:
            return float(hi_c)
        frac = (itl_target_s - lo_i) / (hi_i - lo_i)
        return float(lo_c + frac * (hi_c - lo_c))

    @classmethod
    def from_points(cls, points: List[Dict[str, float]]) -> "DecodeInterpolator":
        return cls(
            [p["concurrency"] for p in points],
            [p["itl_s"] for p in points],
            [p["tokens_per_s"] for p in points],
        )


def load_profile(path: str):
    """Load a profiler sweep file → (PrefillInterpolator, DecodeInterpolator)."""
    with open(path) as f:
        doc = json.load(f)
    return (
        PrefillInterpolator.from_points(doc["prefill"]),
        DecodeInterpolator.from_points(doc["decode"]),
    )
