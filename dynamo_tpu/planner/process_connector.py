"""ProcessConnector: reconcile worker subprocesses to a ReplicaPlan.

Reference parity: components/src/dynamo/planner/kubernetes_connector.py
(KubernetesConnector patches DynamoGraphDeployment replica counts and the
operator reconciles pods). Without k8s, the TPU-native equivalent supervises
OS processes directly: `apply(plan)` spawns or retires worker subprocesses
until the live count per role matches the plan, newest-first retirement,
SIGTERM → grace → SIGKILL (the operator's pod-deletion semantics).

Also readable as the missing piece VERDICT weak #9 called out: the planner
can now close the loop on a real deployment, not just write desired counts
to discovery.
"""

from __future__ import annotations

import asyncio
import signal
import subprocess
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class RoleSpec:
    """How to launch one worker of a role ('decode' / 'prefill')."""

    command: Sequence[str]  # e.g. [sys.executable, "-m", "dynamo_tpu.mocker", ...]
    env: Optional[Dict[str, str]] = None
    grace_period_s: float = 10.0


@dataclass
class _Managed:
    proc: subprocess.Popen
    role: str


class ProcessConnector:
    """Supervises one subprocess per replica; roles sized independently."""

    def __init__(
        self,
        roles: Dict[str, RoleSpec],
        *,
        min_alive: int = 0,
        stdout=None,
    ) -> None:
        self.roles = roles
        self.min_alive = min_alive
        self._stdout = stdout if stdout is not None else subprocess.DEVNULL
        self._procs: Dict[str, List[_Managed]] = {r: [] for r in roles}
        self.applied: Optional[Dict[str, int]] = None

    def alive(self, role: str) -> List[_Managed]:
        """Reap exited processes; return the live set."""
        live = [m for m in self._procs.get(role, []) if m.proc.poll() is None]
        dead = len(self._procs.get(role, [])) - len(live)
        if dead:
            logger.warning("%d %s worker(s) exited on their own", dead, role)
        self._procs[role] = live
        return live

    def counts(self) -> Dict[str, int]:
        return {role: len(self.alive(role)) for role in self.roles}

    async def apply(self, plan) -> None:
        await self.apply_counts(
            {"decode": int(plan.decode), "prefill": int(plan.prefill)},
            reason=plan.reason,
        )

    async def apply_counts(self, desired: Dict[str, int], *, reason: str = "") -> None:
        """Reconcile arbitrary per-role counts (the deploy controller path)."""
        for role, spec in self.roles.items():
            want = max(desired.get(role, 0), self.min_alive)
            live = self.alive(role)  # the same list _spawn appends into
            while len(live) < want:
                self._spawn(role, spec)
            if len(live) > want:
                await self._retire(live[want:], spec)
                del live[want:]
        self.applied = {r: len(v) for r, v in self._procs.items()}
        logger.info("process connector applied: %s (%s)", self.applied, reason)

    def _spawn(self, role: str, spec: RoleSpec) -> _Managed:
        proc = subprocess.Popen(
            list(spec.command),
            env=spec.env,
            stdout=self._stdout,
            stderr=subprocess.STDOUT,
        )
        logger.info("spawned %s worker pid=%d", role, proc.pid)
        m = _Managed(proc=proc, role=role)
        self._procs[role].append(m)
        return m

    async def _retire(self, victims: List[_Managed], spec: RoleSpec) -> None:
        """Newest-first graceful retirement (SIGTERM → grace → SIGKILL)."""
        for m in victims:
            if m.proc.poll() is None:
                m.proc.send_signal(signal.SIGTERM)
        deadline = asyncio.get_running_loop().time() + spec.grace_period_s
        for m in victims:
            while m.proc.poll() is None:
                if asyncio.get_running_loop().time() >= deadline:
                    logger.warning(
                        "%s worker pid=%d ignored SIGTERM; killing",
                        m.role, m.proc.pid,
                    )
                    m.proc.kill()
                    break
                await asyncio.sleep(0.1)
            else:
                logger.info("retired %s worker pid=%d", m.role, m.proc.pid)
        for m in victims:
            try:
                m.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass

    async def close(self) -> None:
        for role, spec in self.roles.items():
            await self._retire(self.alive(role), spec)
            self._procs[role] = []
