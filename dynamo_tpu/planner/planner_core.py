"""The planner core: observe → predict → size → apply.

Reference parity: components/src/dynamo/planner/utils/planner_core.py —
BasePlanner (:258), observe_metrics (:511), update predictors (:607),
_compute_replica_requirements (:668/:775/:823), plan_adjustment (:631) with
chip-budget clamping (:132,:180), run loop (:703). Prefill and decode pools
are sized independently (disaggregated deployments); aggregated deployments
size only the decode pool.
"""

from __future__ import annotations

import asyncio
import math
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from dynamo_tpu.planner.load_predictor import BasePredictor, make_predictor
from dynamo_tpu.planner.perf_interpolation import (
    DecodeInterpolator,
    PrefillInterpolator,
)
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class PlannerConfig:
    adjustment_interval_s: float = 30.0
    ttft_target_s: float = 0.5  # SLA targets (ref: planner_sla args)
    itl_target_s: float = 0.02
    predictor: str = "moving-average"
    min_replicas: int = 1
    max_replicas: int = 8
    # Chip budget clamp (ref: planner_core.py:132 GPU budget)
    chips_per_prefill_worker: int = 1
    chips_per_decode_worker: int = 1
    total_chip_budget: int = 8
    osl_default: float = 128.0  # fallback when no OSL metric yet


@dataclass
class MetricsSnapshot:
    """One observation interval (ref: observe_metrics :511)."""

    request_rate: float = 0.0  # requests/sec
    mean_isl: float = 0.0  # input tokens/request
    mean_osl: float = 0.0  # output tokens/request
    p50_ttft_s: Optional[float] = None
    p50_itl_s: Optional[float] = None


@dataclass
class ReplicaPlan:
    prefill: int
    decode: int
    reason: str = ""


class Planner:
    def __init__(
        self,
        config: PlannerConfig,
        prefill_interp: PrefillInterpolator,
        decode_interp: DecodeInterpolator,
        connector: Any,
        metrics_source: Any,  # async () -> MetricsSnapshot
        *,
        disagg: bool = True,
    ) -> None:
        self.config = config
        self.prefill_interp = prefill_interp
        self.decode_interp = decode_interp
        self.connector = connector
        self.metrics_source = metrics_source
        self.disagg = disagg
        self.rate_pred: BasePredictor = make_predictor(config.predictor)
        self.isl_pred: BasePredictor = make_predictor(config.predictor)
        self.osl_pred: BasePredictor = make_predictor(config.predictor)
        self._task: Optional[asyncio.Task] = None
        self._stop = asyncio.Event()
        self.last_plan: Optional[ReplicaPlan] = None

    # -- sizing math (ref: _compute_replica_requirements) -------------------

    def compute_plan(self) -> Optional[ReplicaPlan]:
        rate = self.rate_pred.predict_next()
        isl = self.isl_pred.predict_next()
        osl = self.osl_pred.predict_next() or self.config.osl_default
        if rate is None or isl is None:
            return None
        cfg = self.config

        # Prefill pool: needed prefill token throughput / per-worker
        # throughput at the SLA'd ISL.
        prefill_tokens_per_s = rate * isl
        per_worker_prefill = max(self.prefill_interp.interpolate_throughput(isl), 1e-6)
        ttft = self.prefill_interp.interpolate_ttft(isl)
        prefill_n = math.ceil(prefill_tokens_per_s / per_worker_prefill)
        if ttft > cfg.ttft_target_s:
            # A single prefill can't meet TTFT at this ISL — chunking across
            # workers doesn't help; flag it but keep the throughput sizing.
            logger.warning(
                "TTFT SLA %.3fs unattainable at ISL %.0f (model TTFT %.3fs)",
                cfg.ttft_target_s, isl, ttft,
            )

        # Decode pool: steady-state concurrency = rate × generation time;
        # cap per-worker concurrency at the ITL SLA crossing.
        max_conc = max(self.decode_interp.max_concurrency_for_itl(cfg.itl_target_s), 1.0)
        per_seq_decode = self.decode_interp.interpolate_throughput(max_conc) / max_conc
        gen_time_s = osl / max(per_seq_decode, 1e-6)
        concurrency = rate * gen_time_s
        decode_n = math.ceil(concurrency / max_conc)

        prefill_n = min(max(prefill_n, cfg.min_replicas), cfg.max_replicas)
        decode_n = min(max(decode_n, cfg.min_replicas), cfg.max_replicas)
        if not self.disagg:
            prefill_n = 0

        # Chip budget clamp (ref: planner_core.py:132): shrink the larger
        # pool first until the budget fits.
        def chips(p: int, d: int) -> int:
            return p * cfg.chips_per_prefill_worker + d * cfg.chips_per_decode_worker

        while chips(prefill_n, decode_n) > cfg.total_chip_budget:
            if prefill_n >= decode_n and prefill_n > cfg.min_replicas:
                prefill_n -= 1
            elif decode_n > cfg.min_replicas:
                decode_n -= 1
            else:
                break
        return ReplicaPlan(
            prefill=prefill_n,
            decode=decode_n,
            reason=(
                f"rate={rate:.2f}req/s isl={isl:.0f} osl={osl:.0f} "
                f"conc={concurrency:.1f}/{max_conc:.1f}per-worker"
            ),
        )

    # -- loop ---------------------------------------------------------------

    async def observe_once(self) -> MetricsSnapshot:
        snap: MetricsSnapshot = await self.metrics_source()
        self.rate_pred.add_data_point(snap.request_rate)
        if snap.mean_isl:
            self.isl_pred.add_data_point(snap.mean_isl)
        if snap.mean_osl:
            self.osl_pred.add_data_point(snap.mean_osl)
        return snap

    async def step(self) -> Optional[ReplicaPlan]:
        await self.observe_once()
        plan = self.compute_plan()
        if plan is not None:
            self.last_plan = plan
            logger.info(
                "plan: prefill=%d decode=%d (%s)", plan.prefill, plan.decode, plan.reason
            )
            await self.connector.apply(plan)
        return plan

    def start(self) -> None:
        if self._task is None:
            self._stop.clear()
            self._task = asyncio.get_event_loop().create_task(
                self._run(), name="planner"
            )

    async def _run(self) -> None:
        while not self._stop.is_set():
            try:
                await self.step()
            except Exception:
                logger.exception("planner step failed")
            try:
                await asyncio.wait_for(
                    self._stop.wait(), timeout=self.config.adjustment_interval_s
                )
            except asyncio.TimeoutError:
                pass

    async def stop(self) -> None:
        self._stop.set()
        if self._task is not None:
            await self._task
            self._task = None
