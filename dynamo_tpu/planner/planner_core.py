"""The planner core: observe → predict → size → apply.

Reference parity: components/src/dynamo/planner/utils/planner_core.py —
BasePlanner (:258), observe_metrics (:511), update predictors (:607),
_compute_replica_requirements (:668/:775/:823), plan_adjustment (:631) with
chip-budget clamping (:132,:180), run loop (:703). Prefill and decode pools
are sized independently (disaggregated deployments); aggregated deployments
size only the decode pool.
"""

from __future__ import annotations

import asyncio
import math
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from dynamo_tpu.planner.feedback import (
    CorrectionFactor,
    FeedbackConfig,
    PlannerMetrics,
)
from dynamo_tpu.planner.load_predictor import BasePredictor, make_predictor
from dynamo_tpu.planner.perf_interpolation import (
    DecodeInterpolator,
    PrefillInterpolator,
)
from dynamo_tpu.runtime import fault_names
from dynamo_tpu.runtime.faults import fault_point
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class PlannerConfig:
    adjustment_interval_s: float = 30.0
    ttft_target_s: float = 0.5  # SLA targets (ref: planner_sla args)
    itl_target_s: float = 0.02
    predictor: str = "moving-average"
    min_replicas: int = 1
    max_replicas: int = 8
    # Chip budget clamp (ref: planner_core.py:132 GPU budget)
    chips_per_prefill_worker: int = 1
    chips_per_decode_worker: int = 1
    total_chip_budget: int = 8
    osl_default: float = 128.0  # fallback when no OSL metric yet
    # Correction-factor feedback (planner/feedback.py): observed/predicted
    # SLA ratios folded into the interpolator outputs so a mis-profiled
    # table heals instead of mis-sizing forever. decay=0 disables.
    feedback: FeedbackConfig = field(default_factory=FeedbackConfig)
    # Intra-chip rebalance before scale-out (tick budgeter): while the
    # fleet's mean prefill-budget headroom (MetricsSnapshot
    # .prefill_budget_frac, 1.0 = budgets at ceiling, 0.0 = all at the
    # starvation floor) is at/above this, an ITL breach holds decode
    # scale-OUT for the interval — the budgeters still have prefill to
    # squeeze on-chip, which is cheaper than a launch. Below it the
    # budgets are spent and the breach sizes the fleet as before.
    # ≥ 1.0 disables the hold (pre-budgeter behavior).
    budget_rebalance_fraction: float = 0.75


@dataclass
class MetricsSnapshot:
    """One observation interval (ref: observe_metrics :511)."""

    request_rate: float = 0.0  # requests/sec
    mean_isl: float = 0.0  # input tokens/request
    mean_osl: float = 0.0  # output tokens/request
    p50_ttft_s: Optional[float] = None
    p50_itl_s: Optional[float] = None
    # Tick-budgeter headroom, mean over BUDGETED workers: 1.0 = budgets
    # at ceiling (throughput mode), 0.5 = adapting, 0.0 = every budgeter
    # at its starvation floor. None = no budgeted workers observed this
    # interval (budgeter off / pre-budgeter fleet) — the rebalance hold
    # never fires on None.
    prefill_budget_frac: Optional[float] = None


@dataclass
class ReplicaPlan:
    prefill: int
    decode: int
    reason: str = ""


class Planner:
    def __init__(
        self,
        config: PlannerConfig,
        prefill_interp: PrefillInterpolator,
        decode_interp: DecodeInterpolator,
        connector: Any,
        metrics_source: Any,  # async () -> MetricsSnapshot
        *,
        disagg: bool = True,
        metrics: Optional[PlannerMetrics] = None,
    ) -> None:
        self.config = config
        self.prefill_interp = prefill_interp
        self.decode_interp = decode_interp
        self.connector = connector
        self.metrics_source = metrics_source
        self.disagg = disagg
        self.rate_pred: BasePredictor = make_predictor(config.predictor)
        self.isl_pred: BasePredictor = make_predictor(config.predictor)
        self.osl_pred: BasePredictor = make_predictor(config.predictor)
        # Correction-factor feedback: one decayed observed/predicted ratio
        # per stage, folded each observation interval and applied to every
        # interpolator read (planner/feedback.py has the math and the
        # fixed-point argument). ``metrics`` may be shared with an
        # ElasticController so the whole planner plane renders as one
        # scrape source.
        self.feedback_ttft = CorrectionFactor(config.feedback)
        self.feedback_itl = CorrectionFactor(config.feedback)
        self.metrics = metrics if metrics is not None else PlannerMetrics()
        self._task: Optional[asyncio.Task] = None
        self._stop = asyncio.Event()
        self.last_plan: Optional[ReplicaPlan] = None
        # Freshest observed p50 ITL (set every observation, gated or
        # not): the scale-down SLA guard reads it in compute_plan.
        self._last_itl: Optional[float] = None
        # Freshest budgeter headroom (None = no budgeted workers): the
        # rebalance-before-launch hold reads it in compute_plan.
        self._last_budget_frac: Optional[float] = None

    # -- sizing math (ref: _compute_replica_requirements) -------------------

    def compute_plan(self) -> Optional[ReplicaPlan]:
        rate = self.rate_pred.predict_next()
        isl = self.isl_pred.predict_next()
        osl = self.osl_pred.predict_next() or self.config.osl_default
        if rate is None or isl is None:
            return None
        cfg = self.config

        # Prefill pool: needed prefill token throughput / per-worker
        # throughput at the SLA'd ISL. The TTFT correction factor scales
        # the table both ways: a 2×-slow fleet quotes 2× the TTFT and
        # half the tokens/sec the sweep promised.
        prefill_tokens_per_s = rate * isl
        per_worker_prefill = max(
            self.feedback_ttft.correct_down(
                self.prefill_interp.interpolate_throughput(isl)
            ),
            1e-6,
        )
        ttft = self.feedback_ttft.correct_up(
            self.prefill_interp.interpolate_ttft(isl)
        )
        prefill_n = math.ceil(prefill_tokens_per_s / per_worker_prefill)
        if ttft > cfg.ttft_target_s:
            # A single prefill can't meet TTFT at this ISL — chunking across
            # workers doesn't help; flag it but keep the throughput sizing.
            logger.warning(
                "TTFT SLA %.3fs unattainable at ISL %.0f (model TTFT %.3fs)",
                cfg.ttft_target_s, isl, ttft,
            )

        # Decode pool: steady-state concurrency = rate × generation time;
        # cap per-worker concurrency at the ITL SLA crossing. The ITL
        # correction factor shifts the crossing: a fleet observed f× slower
        # than the table meets the SLA only up to the concurrency where the
        # TABLE reads itl_target/f (table ITL × f ≤ target ⟺ table ITL ≤
        # target/f), and its per-seq throughput at that point is the
        # table's divided by f.
        max_conc = max(
            self.decode_interp.max_concurrency_for_itl(
                self.feedback_itl.correct_down(cfg.itl_target_s)
            ),
            1.0,
        )
        per_seq_decode = self.feedback_itl.correct_down(
            self.decode_interp.interpolate_throughput(max_conc) / max_conc
        )
        gen_time_s = osl / max(per_seq_decode, 1e-6)
        concurrency = rate * gen_time_s
        decode_n = math.ceil(concurrency / max_conc)

        # SLA-breach scale-down guard: an arrivals-derived rate reads LOW
        # the moment a burst ends, while the backlog it left keeps the
        # fleet saturated — commanding down then drains workers into a
        # fleet with no admission headroom (handoffs refused for
        # capacity, streams to the re-prefill rung) and digs the breach
        # deeper. While observed ITL exceeds the SLA, the decode pool
        # may grow but never shrink below the last plan.
        itl_hold = (
            self._last_itl is not None
            and self._last_itl > cfg.itl_target_s
            and self.last_plan is not None
            and decode_n < self.last_plan.decode
        )
        if itl_hold:
            decode_n = self.last_plan.decode

        # Rebalance-before-launch (tick budgeter): an ITL breach with FAT
        # prefill budgets fleet-wide is an intra-chip imbalance — the
        # budgeters will squeeze prefill within an evaluation window,
        # which is free and instant next to launching a worker. Hold the
        # decode scale-OUT for this interval; if the budgets spend down
        # to the floor (headroom < budget_rebalance_fraction) and ITL
        # still breaches, the next interval scales out for real.
        budget_hold = (
            self._last_itl is not None
            and self._last_itl > cfg.itl_target_s
            and self._last_budget_frac is not None
            and self._last_budget_frac >= cfg.budget_rebalance_fraction
            and self.last_plan is not None
            and decode_n > self.last_plan.decode
        )
        if budget_hold:
            decode_n = self.last_plan.decode

        prefill_n = min(max(prefill_n, cfg.min_replicas), cfg.max_replicas)
        decode_n = min(max(decode_n, cfg.min_replicas), cfg.max_replicas)
        if not self.disagg:
            prefill_n = 0

        # Chip budget clamp (ref: planner_core.py:132): shrink the larger
        # pool first until the budget fits.
        def chips(p: int, d: int) -> int:
            return p * cfg.chips_per_prefill_worker + d * cfg.chips_per_decode_worker

        while chips(prefill_n, decode_n) > cfg.total_chip_budget:
            if prefill_n >= decode_n and prefill_n > cfg.min_replicas:
                prefill_n -= 1
            elif decode_n > cfg.min_replicas:
                decode_n -= 1
            else:
                break
        return ReplicaPlan(
            prefill=prefill_n,
            decode=decode_n,
            reason=(
                f"rate={rate:.2f}req/s isl={isl:.0f} osl={osl:.0f} "
                f"conc={concurrency:.1f}/{max_conc:.1f}per-worker"
                + (" itl-breach-hold" if itl_hold else "")
                + (" budget-rebalance" if budget_hold else "")
            ),
        )

    # -- feedback ------------------------------------------------------------

    def _fold_feedback(self, snap: MetricsSnapshot) -> None:
        """Fold one interval's observed SLA metrics against the raw table
        predictions at the OBSERVED operating point (planner/feedback.py).
        Idle intervals (no completions) fold nothing.

        Scaling transients fold nothing either: completions observed this
        interval were generated by the PREVIOUS fleet size, and folding
        their latency against the current replica count teaches the
        factor phantom slowness (observed: an honest fleet learned a 2.3×
        factor during a 4× down-ramp and briefly quadrupled itself). A
        connector that actuates (ElasticController) exposes
        ``feedback_stable()``; simple connectors don't, and fold always."""
        cfg = self.config
        gate = getattr(self.connector, "feedback_stable", None)
        if gate is not None and not gate():
            self.metrics.correction_factor.set(
                self.feedback_ttft.value, stage="ttft"
            )
            self.metrics.correction_factor.set(
                self.feedback_itl.value, stage="itl"
            )
            return
        if snap.p50_ttft_s is not None and snap.mean_isl > 0:
            self.feedback_ttft.observe(
                snap.p50_ttft_s,
                self.prefill_interp.interpolate_ttft(snap.mean_isl),
            )
        if snap.p50_itl_s is not None and snap.request_rate > 0:
            # Little's law: in-flight streams = rate × stream duration
            # (OSL × observed per-token latency), spread over the decode
            # replicas the last plan asked for.
            osl = snap.mean_osl or cfg.osl_default
            replicas = max(
                self.last_plan.decode if self.last_plan else cfg.min_replicas,
                1,
            )
            conc_per_worker = (
                snap.request_rate * osl * snap.p50_itl_s / replicas
            )
            self.feedback_itl.observe(
                snap.p50_itl_s,
                self.decode_interp.interpolate_itl(conc_per_worker),
            )
        self.metrics.correction_factor.set(
            self.feedback_ttft.value, stage="ttft"
        )
        self.metrics.correction_factor.set(
            self.feedback_itl.value, stage="itl"
        )

    # -- loop ---------------------------------------------------------------

    async def observe_once(self) -> MetricsSnapshot:
        # Chaos seam: an injected failure here models the scrape (or the
        # metrics pipeline) dying BEFORE anything is read — the control
        # loop must skip the interval, never act on a half-read snapshot.
        fault_point(fault_names.PLANNER_OBSERVE)
        snap: MetricsSnapshot = await self.metrics_source()
        if snap.p50_itl_s is not None:
            self._last_itl = snap.p50_itl_s
        # None means "no budgeted workers this interval" and must CLEAR
        # the hold signal (a fleet whose budgeters turned off can't keep
        # deferring launches on a stale headroom reading).
        self._last_budget_frac = snap.prefill_budget_frac
        self.rate_pred.add_data_point(snap.request_rate)
        if snap.mean_isl:
            self.isl_pred.add_data_point(snap.mean_isl)
        if snap.mean_osl:
            self.osl_pred.add_data_point(snap.mean_osl)
        self._fold_feedback(snap)
        return snap

    async def step(self) -> Optional[ReplicaPlan]:
        await self.observe_once()
        plan = self.compute_plan()
        if plan is not None:
            self.last_plan = plan
            self.metrics.desired_replicas.set(plan.prefill, pool="prefill")
            self.metrics.desired_replicas.set(plan.decode, pool="decode")
            logger.info(
                "plan: prefill=%d decode=%d (%s)", plan.prefill, plan.decode, plan.reason
            )
            # Chaos seam: an injected failure models the actuation plane
            # refusing the plan — the loop retries on its own cadence.
            fault_point(fault_names.PLANNER_APPLY)
            self.metrics.applies.inc()
            await self.connector.apply(plan)
        return plan

    def register_metrics(self, server: Any) -> None:
        """Expose the planner families on a SystemStatusServer (safe to
        combine with an ElasticController sharing the same metrics)."""
        self.metrics.register(server)

    def start(self) -> None:
        if self._task is None:
            self._stop.clear()
            # get_running_loop, NOT get_event_loop: starting outside a
            # running loop must fail loudly — the deprecated form silently
            # bound the task to a brand-new never-running loop, a planner
            # that looked started and never planned.
            self._task = asyncio.get_running_loop().create_task(
                self._run(), name="planner"
            )

    async def _run(self) -> None:
        while not self._stop.is_set():
            try:
                await self.step()
            except Exception:
                logger.exception("planner step failed")
            try:
                await asyncio.wait_for(
                    self._stop.wait(), timeout=self.config.adjustment_interval_s
                )
            except asyncio.TimeoutError:
                pass

    async def stop(self) -> None:
        self._stop.set()
        if self._task is not None:
            await self._task
            self._task = None
