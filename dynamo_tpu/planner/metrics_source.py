"""Live metrics for the planner: scrape the frontend's /metrics endpoint.

Reference parity: components/src/dynamo/planner/utils/prometheus.py
(PrometheusAPIClient issuing `increase(..._sum[i])/increase(..._count[i])`
PromQL against a Prometheus server). This environment runs no Prometheus
server, so the TPU-native design scrapes the frontend's Prometheus text
exposition directly and computes the interval deltas client-side — same
inputs to the planner (request rate, mean ISL/OSL, TTFT/ITL) without the
extra hop. Multiple frontends can be scraped; series are summed.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, Iterable, List, Mapping, Optional, Tuple

from dynamo_tpu.planner.planner_core import MetricsSnapshot
from dynamo_tpu.runtime.metric_names import (
    ENGINE_BUDGET_STATE,
    FRONTEND_INPUT_TOKENS_TOTAL,
    FRONTEND_ITL,
    FRONTEND_OUTPUT_TOKENS_TOTAL,
    FRONTEND_REQUESTS_TOTAL,
    FRONTEND_TTFT,
)
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# (series name, sorted label items) -> value
Sample = Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float]


def parse_prometheus_text(text: str) -> Sample:
    """Parse Prometheus text exposition into a flat sample dict.

    Handles counters/gauges/histogram series with labels; ignores comments,
    timestamps, and malformed lines (scrape robustness over strictness).
    """
    out: Sample = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            if "{" in line:
                name, rest = line.split("{", 1)
                labels_raw, tail = rest.rsplit("}", 1)
                labels = []
                for part in _split_labels(labels_raw):
                    k, v = part.split("=", 1)
                    labels.append((k, v.strip('"')))
                value = float(tail.split()[0])
                out[(name, tuple(sorted(labels)))] = value
            else:
                parts = line.split()
                out[(parts[0], ())] = float(parts[1])
        except (ValueError, IndexError):
            continue
    return out


def _split_labels(raw: str) -> List[str]:
    """Split label pairs on commas outside quotes."""
    parts: List[str] = []
    buf = ""
    in_q = False
    for ch in raw:
        if ch == '"':
            in_q = not in_q
        if ch == "," and not in_q:
            if buf:
                parts.append(buf)
            buf = ""
        else:
            buf += ch
    if buf:
        parts.append(buf)
    return parts


def _sum_series(sample: Sample, name: str, where: Mapping[str, str] = {}) -> float:
    total = 0.0
    for (n, labels), v in sample.items():
        if n != name:
            continue
        d = dict(labels)
        if all(d.get(k) == val for k, val in where.items()):
            total += v
    return total


def _bucket_deltas(
    prev: Sample, cur: Sample, name: str
) -> List[Tuple[float, float]]:
    """[(le, count_delta)] for a histogram, ascending by bound."""
    acc: Dict[float, float] = {}
    for (n, labels), v in cur.items():
        if n != f"{name}_bucket":
            continue
        d = dict(labels)
        le = float("inf") if d.get("le") == "+Inf" else float(d.get("le", "inf"))
        acc[le] = acc.get(le, 0.0) + (v - prev.get((n, labels), 0.0))
    return sorted(acc.items())


def _histogram_quantile(deltas: List[Tuple[float, float]], q: float) -> Optional[float]:
    """Prometheus-style histogram_quantile over cumulative bucket deltas."""
    if not deltas:
        return None
    total = deltas[-1][1]
    if total <= 0:
        return None
    target = q * total
    lo_bound, lo_count = 0.0, 0.0
    for le, count in deltas:
        if count >= target:
            if le == float("inf"):
                return lo_bound
            span = count - lo_count
            frac = (target - lo_count) / span if span > 0 else 1.0
            return lo_bound + (le - lo_bound) * frac
        lo_bound, lo_count = le, count
    return lo_bound


# Budget-state gauge value → prefill-budget headroom. OFF (0) is absent:
# an unbudgeted worker contributes no signal (a mixed fleet's mean speaks
# only for the budgeted workers the planner could rebalance).
_BUDGET_HEADROOM = {1: 1.0, 2: 0.5, 3: 0.0}


def _budget_headroom(sample: Sample) -> Optional[float]:
    """Mean tick-budgeter headroom across scraped workers (None when no
    worker advertises a running budgeter) — compute_plan's rebalance-
    before-launch signal. Gauges, not counters: the CURRENT scrape is the
    state; no delta against the baseline."""
    vals = [
        _BUDGET_HEADROOM[int(v)]
        for (name, _labels), v in sample.items()
        if name == ENGINE_BUDGET_STATE and int(v) in _BUDGET_HEADROOM
    ]
    return sum(vals) / len(vals) if vals else None


@dataclass
class _Scrape:
    ts: float
    sample: Sample


class FrontendScrapeSource:
    """Async callable yielding a MetricsSnapshot per adjustment interval.

    First call primes the baseline and reports zeros; subsequent calls report
    deltas since the previous call (the reference's `increase(m[interval])`).
    """

    def __init__(
        self, urls: Iterable[str], *, model: Optional[str] = None, timeout_s: float = 5.0
    ) -> None:
        self.urls = list(urls)
        self.model = model
        self.timeout_s = timeout_s
        self._prev: Optional[_Scrape] = None

    async def _fetch(self) -> Sample:
        import aiohttp

        merged: Sample = {}
        async with aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=self.timeout_s)
        ) as session:
            for url in self.urls:
                try:
                    async with session.get(url) as resp:
                        text = await resp.text()
                except Exception as exc:
                    logger.warning("metrics scrape of %s failed: %s", url, exc)
                    continue
                for key, v in parse_prometheus_text(text).items():
                    merged[key] = merged.get(key, 0.0) + v
        return merged

    def snapshot_from(self, prev: Sample, cur: Sample, dt: float) -> MetricsSnapshot:
        where = {"model": self.model} if self.model else {}
        name = FRONTEND_REQUESTS_TOTAL
        # completed requests across endpoints/statuses
        req_delta = _sum_series(cur, name, where) - _sum_series(prev, name, where)
        in_delta = _sum_series(cur, FRONTEND_INPUT_TOKENS_TOTAL, where) - _sum_series(
            prev, FRONTEND_INPUT_TOKENS_TOTAL, where
        )
        out_delta = _sum_series(cur, FRONTEND_OUTPUT_TOKENS_TOTAL, where) - _sum_series(
            prev, FRONTEND_OUTPUT_TOKENS_TOTAL, where
        )
        ttft = _histogram_quantile(
            _bucket_deltas(prev, cur, FRONTEND_TTFT), 0.5
        )
        itl = _histogram_quantile(
            _bucket_deltas(prev, cur, FRONTEND_ITL), 0.5
        )
        rate = req_delta / dt if dt > 0 else 0.0
        return MetricsSnapshot(
            request_rate=max(rate, 0.0),
            mean_isl=in_delta / req_delta if req_delta > 0 else 0.0,
            mean_osl=out_delta / req_delta if req_delta > 0 else 0.0,
            p50_ttft_s=ttft,
            p50_itl_s=itl,
            prefill_budget_frac=_budget_headroom(cur),
        )

    async def __call__(self) -> MetricsSnapshot:
        now = time.monotonic()
        cur = await self._fetch()
        prev = self._prev
        self._prev = _Scrape(now, cur)
        if prev is None:
            return MetricsSnapshot()
        return self.snapshot_from(prev.sample, cur, now - prev.ts)
