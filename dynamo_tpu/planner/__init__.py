"""SLA-driven autoscaler (the Planner).

Reference parity: components/src/dynamo/planner — BasePlanner
(utils/planner_core.py:258, plan_adjustment :631, run :703), load predictors
(utils/load_predictor.py:97–320), perf interpolation from profiler sweeps
(utils/perf_interpolation.py:37,102), scaling connectors (kubernetes /
virtual). Here the TPU deployment unit is a worker process on a slice;
the virtual connector drives process-level scaling for tests and single-host
deployments, the k8s connector patches CRs (deploy/ round 2+).
"""

from dynamo_tpu.planner.load_predictor import (
    ConstantPredictor,
    KalmanPredictor,
    MovingAveragePredictor,
    make_predictor,
)
from dynamo_tpu.planner.perf_interpolation import (
    DecodeInterpolator,
    PrefillInterpolator,
)
from dynamo_tpu.planner.planner_core import (
    MetricsSnapshot,
    Planner,
    PlannerConfig,
    ReplicaPlan,
)
from dynamo_tpu.planner.connectors import VirtualConnector
from dynamo_tpu.planner.elastic import ElasticConfig, ElasticController
from dynamo_tpu.planner.feedback import (
    CorrectionFactor,
    FeedbackConfig,
    PlannerMetrics,
)
from dynamo_tpu.planner.metrics_source import FrontendScrapeSource
from dynamo_tpu.planner.process_connector import ProcessConnector, RoleSpec
from dynamo_tpu.planner.simfleet import (
    SimConfig,
    SimFleet,
    expected_tokens,
    profile_interpolators,
)

__all__ = [
    "CorrectionFactor",
    "ElasticConfig",
    "ElasticController",
    "FeedbackConfig",
    "FrontendScrapeSource",
    "PlannerMetrics",
    "ProcessConnector",
    "RoleSpec",
    "SimConfig",
    "SimFleet",
    "ConstantPredictor",
    "KalmanPredictor",
    "MovingAveragePredictor",
    "expected_tokens",
    "make_predictor",
    "profile_interpolators",
    "DecodeInterpolator",
    "PrefillInterpolator",
    "MetricsSnapshot",
    "Planner",
    "PlannerConfig",
    "ReplicaPlan",
    "VirtualConnector",
]
