"""Correction-factor feedback: the planner's self-healing loop over a
mis-profiled interpolation table.

The sizing math (planner_core.py) interpolates TTFT/ITL/throughput from a
profiler sweep — a STATIC table. A table profiled on different silicon, a
stale model revision, or an optimistic benchmark never heals: the planner
keeps sizing for the fleet it was promised, not the fleet it has (VERDICT
Missing #5). This module closes the loop:

    factor = EWMA( observed_SLA_metric / table_predicted_SLA_metric )

folded per adjustment interval with decay, one factor per stage:

  * ``ttft`` — observed p50 TTFT vs the prefill table's TTFT at the
    observed mean ISL. A factor of 2 means prefill is twice as slow as
    profiled: the corrected table quotes 2× the TTFT and 1/2 the prefill
    tokens/sec, so the prefill pool doubles.
  * ``itl`` — observed p50 ITL vs the decode table's ITL at the estimated
    per-worker concurrency (Little's law: rate × OSL × observed ITL gives
    in-flight streams, divided by the applied decode replica count). A
    factor of 2 halves the ITL-SLA concurrency crossing and the per-seq
    decode throughput, so the decode pool doubles.

Factors are clamped (default [1/8, 8]): queueing transients under overload
inflate observed latency far past any honest hardware mis-profile, and an
unclamped factor would let one bad interval command an 80× fleet. The
fixed point is exact: when the real system is k× slower than the table,
the ratio reads k at EVERY operating point of a proportionally-wrong
table, the factor converges to k (geometrically, at the EWMA rate), and
the corrected sizing equals what an honest table would produce — the
convergence simulation in tests/test_planner.py drives a 2×-wrong table
to the oracle plan in a bounded number of intervals.

Factors are exposed as lint-pinned gauges
(``dynamo_tpu_planner_correction_factor{stage}``, metric_names.py
ALL_PLANNER) so a drifting profile is an alertable signal, not a silent
capacity shortfall.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

from dynamo_tpu.runtime import metric_names as mn
from dynamo_tpu.runtime.metrics_core import MetricsRegistry


@dataclass(frozen=True)
class FeedbackConfig:
    """``decay``: EWMA weight of the newest ratio (0 disables feedback —
    the factor never moves off 1.0). ``min_factor``/``max_factor``: clamp
    on each folded ratio AND the factor itself."""

    decay: float = 0.4
    min_factor: float = 0.125
    max_factor: float = 8.0
    # Ratios within 1 ± deadband fold as exactly 1.0: measurement noise
    # (median quirks, churn transients) must not walk the factor off an
    # honest table — a genuine mis-profile smaller than the deadband
    # stays uncorrected by design (it is also too small to mis-size by a
    # whole replica at any realistic pool).
    deadband: float = 0.05

    def __post_init__(self) -> None:
        if not 0.0 <= self.decay <= 1.0:
            raise ValueError("decay must be in [0, 1]")
        if not 0.0 < self.min_factor <= 1.0 <= self.max_factor:
            raise ValueError("need min_factor <= 1 <= max_factor")
        if self.deadband < 0.0:
            raise ValueError("deadband must be >= 0")


class CorrectionFactor:
    """One stage's decayed observed/predicted ratio, starting honest (1.0)."""

    def __init__(self, config: FeedbackConfig) -> None:
        self.config = config
        self.value = 1.0
        self.observations = 0

    def observe(self, observed: Optional[float], predicted: float) -> None:
        """Fold one interval's (observed, table-predicted) pair. Missing or
        non-positive observations (no traffic this interval) are skipped —
        an idle fleet is not evidence about the table."""
        cfg = self.config
        if cfg.decay <= 0.0:
            return
        if observed is None or observed <= 0.0 or predicted <= 0.0:
            return
        ratio = min(max(observed / predicted, cfg.min_factor), cfg.max_factor)
        if abs(ratio - 1.0) <= cfg.deadband:
            ratio = 1.0
        self.value = cfg.decay * ratio + (1.0 - cfg.decay) * self.value
        self.value = min(max(self.value, cfg.min_factor), cfg.max_factor)
        self.observations += 1

    def correct_up(self, predicted: float) -> float:
        """Latency-shaped prediction (TTFT/ITL): slower fleet → larger."""
        return predicted * self.value

    def correct_down(self, predicted: float) -> float:
        """Rate-shaped prediction (tokens/sec, concurrency): slower fleet
        → smaller."""
        return predicted / self.value


class PlannerMetrics:
    """Canonical planner families (runtime/metric_names.py ALL_PLANNER).

    One registry shared by the sizing loop (correction factors, desired
    replicas) and the elastic controller (state machine, holds, drains) —
    the planner plane renders as one scrape source."""

    def __init__(self) -> None:
        self.registry = MetricsRegistry()
        # Servers this registry is already a scrape source on: the
        # planner AND the elastic controller usually share one
        # PlannerMetrics, and both expose register_metrics — without the
        # guard, registering both renders every family twice per scrape.
        self._registered_servers: set = set()
        self.correction_factor = self.registry.gauge(
            mn.PLANNER_CORRECTION_FACTOR,
            "Decayed EWMA of observed/predicted SLA ratio folded into the "
            "interpolator outputs, by stage (ttft | itl); 1.0 = the "
            "profile table is honest",
            ["stage"],
        )
        self.desired_replicas = self.registry.gauge(
            mn.PLANNER_DESIRED_REPLICAS,
            "Last computed plan per pool (prefill | decode)",
            ["pool"],
        )
        self.state = self.registry.gauge(
            mn.PLANNER_STATE,
            "Plan-transition state machine: 0 steady, 1 scaling_up, "
            "2 scaling_down, 3 converged (actuation done, cooldown)",
        )
        self.transitions = self.registry.counter(
            mn.PLANNER_TRANSITIONS_TOTAL,
            "Plan-state transitions, by destination state",
            ["to"],
        )
        self.applies = self.registry.counter(
            mn.PLANNER_APPLIES_TOTAL,
            "Plans handed to the scaling connector",
        )
        self.holds = self.registry.counter(
            mn.PLANNER_HOLDS_TOTAL,
            "Plan changes suppressed by hysteresis streaks or the "
            "post-actuation cooldown (oscillating load lands here instead "
            "of flapping the fleet)",
        )
        self.scale_down_drains = self.registry.counter(
            mn.PLANNER_SCALE_DOWN_DRAINS_TOTAL,
            "Workers retired through drain-with-handoff, by mode "
            "(planned = planner scale-down, preemption = spot reclaim)",
            ["mode"],
        )
        self.scale_up_pending = self.registry.gauge(
            mn.PLANNER_SCALE_UP_PENDING,
            "Replicas launched but not yet ready, per pool: a scale-up "
            "only counts once /readyz (warm restore included) goes green",
            ["pool"],
        )

    def render(self, openmetrics: bool = False) -> str:
        return self.registry.render(openmetrics=openmetrics)

    def register(self, server: Any) -> None:
        """Idempotent per server: sharers may all call this."""
        if id(server) in self._registered_servers:
            return
        self._registered_servers.add(id(server))
        server.register_metrics(self.render)

    def snapshot(self) -> Dict[str, Any]:
        """Host-side mirror for tests/bench (no scrape parsing)."""
        return {
            "correction_ttft": self.correction_factor.value(stage="ttft"),
            "correction_itl": self.correction_factor.value(stage="itl"),
            "state": self.state.value(),
            "applies": self.applies.value(),
            "holds": self.holds.value(),
        }
