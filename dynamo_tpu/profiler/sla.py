"""SLA-driven deployment recommendation (the DGDR profiling role).

Reference parity: the reference's SLA-profiling flow — a
DynamoGraphDeploymentRequest triggers profiling sweeps across parallelism
configs, then recommends the deployment that meets TTFT/ITL targets with
the best goodput per accelerator (profiler + planner pre_swept_results).

Here: given per-config profile sweeps (from profiler.profile_engine on
real hardware, or loaded tables), ``recommend`` picks the config that
meets the SLA at the target workload with the fewest chips, and sizes the
worker pools for the expected request rate using the planner's own math.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from dynamo_tpu.planner.perf_interpolation import (
    DecodeInterpolator,
    PrefillInterpolator,
)


@dataclass
class SlaTargets:
    ttft_s: float = 0.5
    itl_s: float = 0.02


@dataclass
class Workload:
    request_rate: float  # requests/sec to provision for
    isl: float = 512.0
    osl: float = 128.0


@dataclass
class ConfigProfile:
    """One parallelism config's measured profile."""

    name: str  # e.g. "tp1", "tp4"
    chips_per_worker: int
    prefill_points: List[Dict[str, float]]  # profiler prefill sweep rows
    decode_points: List[Dict[str, float]]  # profiler decode sweep rows


@dataclass
class Recommendation:
    config_name: str
    chips_per_worker: int
    prefill_workers: int
    decode_workers: int
    total_chips: int
    ttft_s: float  # predicted at the workload ISL
    itl_s: float  # predicted at the chosen concurrency
    goodput_per_chip: float  # output tokens/sec/chip at the SLA point
    reason: str = ""


@dataclass
class SlaReport:
    chosen: Optional[Recommendation]
    rejected: Dict[str, str] = field(default_factory=dict)  # config → why

    def summary(self) -> str:
        if self.chosen is None:
            return f"no config meets the SLA ({len(self.rejected)} rejected)"
        c = self.chosen
        return (
            f"{c.config_name}: {c.prefill_workers}P+{c.decode_workers}D × "
            f"{c.chips_per_worker} chip(s) = {c.total_chips} chips, "
            f"TTFT {c.ttft_s * 1e3:.0f}ms, ITL {c.itl_s * 1e3:.1f}ms, "
            f"{c.goodput_per_chip:.0f} tok/s/chip"
        )


def _size_config(
    profile: ConfigProfile, targets: SlaTargets, workload: Workload
) -> Recommendation:
    """Planner sizing math for one config (raises ValueError if SLA-infeasible)."""
    pre = PrefillInterpolator.from_points(profile.prefill_points)
    dec = DecodeInterpolator.from_points(profile.decode_points)

    ttft = pre.interpolate_ttft(workload.isl)
    if ttft > targets.ttft_s:
        raise ValueError(
            f"TTFT {ttft * 1e3:.0f}ms > target {targets.ttft_s * 1e3:.0f}ms "
            f"at ISL {workload.isl:.0f}"
        )
    max_conc = dec.max_concurrency_for_itl(targets.itl_s)
    if max_conc < 1.0:
        itl1 = dec.interpolate_itl(1.0)
        raise ValueError(
            f"ITL {itl1 * 1e3:.1f}ms > target {targets.itl_s * 1e3:.1f}ms "
            "even at concurrency 1"
        )

    # Prefill pool sized by token throughput; decode pool by concurrency.
    prefill_tput = max(pre.interpolate_throughput(workload.isl), 1e-6)
    prefill_n = max(math.ceil(workload.request_rate * workload.isl / prefill_tput), 1)

    decode_tput = dec.interpolate_throughput(max_conc)
    per_seq = decode_tput / max_conc
    gen_time_s = workload.osl / max(per_seq, 1e-6)
    concurrency = workload.request_rate * gen_time_s
    decode_n = max(math.ceil(concurrency / max_conc), 1)

    total_chips = (prefill_n + decode_n) * profile.chips_per_worker
    return Recommendation(
        config_name=profile.name,
        chips_per_worker=profile.chips_per_worker,
        prefill_workers=prefill_n,
        decode_workers=decode_n,
        total_chips=total_chips,
        ttft_s=ttft,
        itl_s=dec.interpolate_itl(max_conc),
        goodput_per_chip=decode_tput / profile.chips_per_worker,
        reason=(
            f"conc {concurrency:.1f} / {max_conc:.1f} per worker, "
            f"prefill {workload.request_rate * workload.isl:.0f} tok/s"
        ),
    )


def recommend(
    profiles: List[ConfigProfile], targets: SlaTargets, workload: Workload
) -> SlaReport:
    """Pick the SLA-feasible config with the fewest total chips (goodput per
    chip breaks ties)."""
    report = SlaReport(chosen=None)
    candidates: List[Recommendation] = []
    for profile in profiles:
        try:
            candidates.append(_size_config(profile, targets, workload))
        except ValueError as exc:
            report.rejected[profile.name] = str(exc)
    if candidates:
        report.chosen = min(
            candidates, key=lambda r: (r.total_chips, -r.goodput_per_chip)
        )
    return report


async def profile_and_recommend(
    engines: Dict[str, tuple],  # name → (engine, chips_per_worker)
    targets: SlaTargets,
    workload: Workload,
    **sweep_kwargs,
) -> SlaReport:
    """Sweep each live engine config then recommend (the end-to-end DGDR
    flow; sweeps run sequentially to keep the device to one config)."""
    from dynamo_tpu.profiler import profile_engine

    profiles = []
    for name, (engine, chips) in engines.items():
        prof = await profile_engine(engine, **sweep_kwargs)
        profiles.append(
            ConfigProfile(
                name=name,
                chips_per_worker=chips,
                prefill_points=prof["prefill"],
                decode_points=prof["decode"],
            )
        )
    return recommend(profiles, targets, workload)
