"""Profiler: sweep an engine to produce planner interpolation tables.

Reference parity: the SLA profiler sweeps behind
DynamoGraphDeploymentRequest + planner/utils/pre_swept_results (SURVEY §2.2
planner row; tests/profiler/). Measures, on the live engine:

  prefill: per-ISL time-to-first-token and prefill tokens/sec
  decode:  per-concurrency inter-token latency and total decode tokens/sec

Output JSON: {"prefill": [{isl, ttft_s, tokens_per_s}...],
              "decode": [{concurrency, itl_s, tokens_per_s}...]}
(consumed by planner.perf_interpolation.load_profile).
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


async def _run_request(engine, tokens, max_tokens):
    req = PreprocessedRequest(
        token_ids=list(tokens),
        sampling=SamplingOptions(temperature=1.0),
        stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
    )
    t0 = time.monotonic()
    ttft = None
    n = 0
    async for out in engine.generate(req, Context()):
        if out.token_ids:
            if ttft is None:
                ttft = time.monotonic() - t0
            n += len(out.token_ids)
    return n, ttft, time.monotonic() - t0


async def profile_prefill(
    engine, isl_values: Sequence[int], *, repeats: int = 3, vocab: int = 256
) -> List[Dict[str, float]]:
    rng = np.random.default_rng(0)
    points = []
    for isl in isl_values:
        ttfts = []
        for r in range(repeats):
            tokens = rng.integers(4, vocab, size=isl).tolist()
            _, ttft, _ = await _run_request(engine, tokens, max_tokens=1)
            if ttft is not None:
                ttfts.append(ttft)
        ttft_s = float(np.median(ttfts)) if ttfts else float("nan")
        points.append(
            {"isl": float(isl), "ttft_s": ttft_s, "tokens_per_s": isl / ttft_s if ttft_s else 0.0}
        )
        logger.info("prefill sweep isl=%d ttft=%.4fs", isl, ttft_s)
    return points


async def profile_decode(
    engine,
    concurrency_values: Sequence[int],
    *,
    isl: int = 64,
    osl: int = 32,
    vocab: int = 256,
) -> List[Dict[str, float]]:
    rng = np.random.default_rng(1)
    points = []
    for conc in concurrency_values:
        prompts = [rng.integers(4, vocab, size=isl).tolist() for _ in range(conc)]
        t0 = time.monotonic()
        results = await asyncio.gather(
            *(_run_request(engine, p, max_tokens=osl) for p in prompts)
        )
        wall = time.monotonic() - t0
        total = sum(r[0] for r in results)
        itls = [
            (r[2] - r[1]) / max(r[0] - 1, 1) for r in results if r[1] is not None
        ]
        itl_s = float(np.median(itls)) if itls else float("nan")
        points.append(
            {
                "concurrency": float(conc),
                "itl_s": itl_s,
                "tokens_per_s": total / wall if wall > 0 else 0.0,
            }
        )
        logger.info("decode sweep conc=%d itl=%.4fs tput=%.1f", conc, itl_s, total / wall)
    return points


async def profile_engine(
    engine,
    *,
    isl_values: Sequence[int] = (64, 128, 256, 512),
    concurrency_values: Sequence[int] = (1, 2, 4, 8),
    osl: int = 32,
    vocab: int = 256,
) -> Dict[str, Any]:
    prefill = await profile_prefill(engine, isl_values, vocab=vocab)
    decode = await profile_decode(
        engine, concurrency_values, isl=min(isl_values), osl=osl, vocab=vocab
    )
    return {"prefill": prefill, "decode": decode}
