"""Canonical environment-variable registry.

Reference parity: lib/runtime/src/config/environment_names.rs (the DYN_*
namespace). All environment knobs used anywhere in dynamo_tpu are declared
here with defaults and documentation; modules read through ``env_*`` helpers
so `python -m dynamo_tpu.cli env` can print the full registry.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

_REGISTRY: Dict[str, "EnvVar"] = {}


@dataclass(frozen=True)
class EnvVar:
    name: str
    default: Any
    parser: Callable[[str], Any]
    doc: str

    def get(self) -> Any:
        raw = os.environ.get(self.name)
        if raw is None:
            return self.default
        try:
            return self.parser(raw)
        except (ValueError, TypeError):
            return self.default


def _register(name: str, default: Any, parser: Callable[[str], Any], doc: str) -> EnvVar:
    var = EnvVar(name, default, parser, doc)
    _REGISTRY[name] = var
    return var


def _parse_bool(raw: str) -> bool:
    return raw.strip().lower() in ("1", "true", "yes", "on")


def env_str(name: str, default: str, doc: str = "") -> EnvVar:
    return _REGISTRY.get(name) or _register(name, default, str, doc)


def env_int(name: str, default: int, doc: str = "") -> EnvVar:
    return _REGISTRY.get(name) or _register(name, default, int, doc)


def env_float(name: str, default: float, doc: str = "") -> EnvVar:
    return _REGISTRY.get(name) or _register(name, default, float, doc)


def env_bool(name: str, default: bool, doc: str = "") -> EnvVar:
    return _REGISTRY.get(name) or _register(name, default, _parse_bool, doc)


def registry() -> Dict[str, EnvVar]:
    return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# Canonical knobs (ref: environment_names.rs). DYN_TPU_* namespace.
# ---------------------------------------------------------------------------

NAMESPACE = env_str("DYN_TPU_NAMESPACE", "dynamo", "Default namespace for components")
REQUEST_PLANE = env_str(
    "DYN_TPU_REQUEST_PLANE", "tcp",
    "Request plane for cross-process serving: tcp|http|local"
)
DISCOVERY = env_str(
    "DYN_TPU_DISCOVERY", "memory", "Discovery backend: memory|file|discd (addr via DYN_TPU_DISCOVERY_ADDR)"
)
DISCOVERY_ADDR = env_str(
    "DYN_TPU_DISCOVERY_ADDR", "127.0.0.1:6180", "discd service address or file-backend directory"
)
EVENT_PLANE = env_str("DYN_TPU_EVENT_PLANE", "zmq", "Event plane: memory|zmq")
EVENT_PLANE_ADDR = env_str(
    "DYN_TPU_EVENT_PLANE_ADDR",
    "127.0.0.1:6181:6182",
    "ZMQ event broker address host:xsub_port:xpub_port",
)
TCP_HOST = env_str(
    "DYN_TPU_TCP_HOST", "127.0.0.1", "Advertised host for the TCP request plane"
)
LEASE_TTL = env_float("DYN_TPU_LEASE_TTL", 10.0, "Discovery lease TTL seconds")
KV_QUANT_AUTO_CTX = env_int(
    "DYN_TPU_KV_QUANT_AUTO_CTX", 512,
    "kv_cache_dtype=auto: quantize the KV cache to int8 when max_model_len "
    "reaches this (measured v5e break-even: int8 KV loses ~3.6 ms/step at "
    "ctx<=160 from scale DMAs, wins beyond a few hundred tokens and "
    "doubles pool capacity)",
)
FLIGHT_DUMP_DIR = env_str(
    "DYN_TPU_FLIGHT_DUMP_DIR", "",
    "Directory for engine flight-recorder JSON dumps on tick abort "
    "(empty = system temp dir)",
)
LOG_LEVEL = env_str("DYN_TPU_LOG", "info", "Log level (trace|debug|info|warn|error)")
LOG_JSON = env_bool("DYN_TPU_LOG_JSON", False, "Emit JSONL structured logs")
HTTP_HOST = env_str("DYN_TPU_HTTP_HOST", "0.0.0.0", "Frontend HTTP bind host")
HTTP_PORT = env_int("DYN_TPU_HTTP_PORT", 8000, "Frontend HTTP bind port")
SYSTEM_PORT = env_int(
    "DYN_TPU_SYSTEM_PORT", 9090, "System status server port (/health /live /metrics)"
)
KV_BLOCK_SIZE = env_int("DYN_TPU_KV_BLOCK_SIZE", 64, "KV cache block size in tokens")
ROUTER_TEMPERATURE = env_float(
    "DYN_TPU_ROUTER_TEMPERATURE", 0.0, "KV router softmax sampling temperature (0 = argmin)"
)
ROUTER_OVERLAP_WEIGHT = env_float(
    "DYN_TPU_ROUTER_OVERLAP_WEIGHT", 1.0, "KV router overlap score weight"
)
MIGRATION_LIMIT = env_int(
    "DYN_TPU_MIGRATION_LIMIT", 3, "Max per-request migrations to new workers on stream death"
)
# -- overload armor (runtime/overload.py; docs/design_docs/overload_control.md)
OVERLOAD_MAX_CONCURRENCY = env_int(
    "DYN_TPU_OVERLOAD_MAX_CONCURRENCY", 256,
    "Frontend streams generating concurrently; excess queues (EDF)",
)
OVERLOAD_MAX_QUEUE = env_int(
    "DYN_TPU_OVERLOAD_MAX_QUEUE", 1024,
    "Bounded admission queue depth; beyond it requests shed 429",
)
OVERLOAD_MAX_QUEUE_DELAY_S = env_float(
    "DYN_TPU_OVERLOAD_MAX_QUEUE_DELAY_S", 30.0,
    "Shed when predicted queue delay exceeds this (429 + Retry-After)",
)
OVERLOAD_DEFAULT_DEADLINE_S = env_float(
    "DYN_TPU_OVERLOAD_DEFAULT_DEADLINE_S", 0.0,
    "Deadline stamped on requests that carry none (0 = unbounded)",
)
OVERLOAD_ITL_SLA_MS = env_float(
    "DYN_TPU_OVERLOAD_ITL_SLA_MS", 0.0,
    "p50 ITL SLA driving healthy->brownout->shed (0 = brownout disabled; "
    "admission caps still enforce)",
)
OVERLOAD_BROWNOUT_MAX_TOKENS = env_int(
    "DYN_TPU_OVERLOAD_BROWNOUT_MAX_TOKENS", 256,
    "max_tokens clamp applied while browned out",
)
# -- trajectory plane (runtime/trajectory.py; docs/design_docs/request_trajectory.md)
TRAJECTORY_RECENT = env_int(
    "DYN_TPU_TRAJECTORY_RECENT", 256,
    "Recent request trajectories retained for GET /debug/trajectory",
)
TRAJECTORY_SLOW = env_int(
    "DYN_TPU_TRAJECTORY_SLOW", 64,
    "Slow/errored trajectory summaries retained past recent-ring eviction",
)
TRAJECTORY_SHIP_INTERVAL_S = env_float(
    "DYN_TPU_TRAJECTORY_SHIP_S", 0.5,
    "Worker-side finished-span batch flush cadence onto the event plane",
)
SLO_TTFT_MS = env_float(
    "DYN_TPU_SLO_TTFT_MS", 0.0,
    "TTFT SLA for the goodput/burn-rate gauges (0 = SLO tracking off)",
)
SLO_ITL_MS = env_float(
    "DYN_TPU_SLO_ITL_MS", 0.0,
    "Mean-ITL SLA for the goodput/burn-rate gauges (0 = SLO tracking off)",
)
SLO_TARGET = env_float(
    "DYN_TPU_SLO_TARGET", 0.99,
    "SLO target the burn-rate denominates against (error budget = 1 - target)",
)
# -- crash plane (runtime/liveness.py; docs/design_docs/fault_tolerance.md)
LOAD_REPORT_INTERVAL_S = env_float(
    "DYN_TPU_LOAD_REPORT_INTERVAL_S", 1.0,
    "Worker load-report publish cadence (router/publisher.py "
    "LoadPublisher). The liveness detection budget is denominated in "
    "these intervals, so shrinking it tightens dead-worker detection",
)
LIVENESS_INTERVAL_S = env_float(
    "DYN_TPU_LIVENESS_INTERVAL_S", 1.0,
    "Expected worker load-report cadence the frontend's liveness tracker "
    "judges missed intervals against (match the LoadPublisher interval)",
)
LIVENESS_SUSPECT_AFTER = env_int(
    "DYN_TPU_LIVENESS_SUSPECT_AFTER", 2,
    "Missed load-report intervals before a worker is SUSPECT",
)
LIVENESS_DEAD_AFTER = env_int(
    "DYN_TPU_LIVENESS_DEAD_AFTER", 5,
    "Missed load-report intervals before a worker is DEAD: drop_worker "
    "reconciliation runs and its in-flight streams abort into migration "
    "(detection-to-migration is bounded by dead_after x interval)",
)
WORKER_ID = env_int(
    "DYN_TPU_WORKER_ID", 0,
    "Stable worker identity across restarts (0 = random per start). A "
    "restarted worker re-registers under the SAME id with a fresh "
    "incarnation so warm rejoin and incarnation fencing line up",
)
GRACE_PERIOD = env_float("DYN_TPU_GRACE_PERIOD", 30.0, "Graceful-shutdown drain seconds")
DRAIN_DEADLINE_S = env_float(
    "DYN_TPU_DRAIN_DEADLINE_S", 30.0,
    "Live-handoff drain budget (SIGTERM / POST /drain / preStop): handoffs "
    "not completed by then fall back to re-prefill migration",
)
DRAIN_HANDOFF_CONCURRENCY = env_int(
    "DYN_TPU_DRAIN_HANDOFF_CONCURRENCY", 4,
    "Concurrent handoff ships per drain: detach/export serialize at the "
    "engine's reconciled boundary, but the peer accept-ack round trips "
    "are independent — pipelining them keeps a full worker's drain "
    "inside the deadline on a slow link",
)
