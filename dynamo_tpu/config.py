"""Canonical environment-variable registry.

Reference parity: lib/runtime/src/config/environment_names.rs (the DYN_*
namespace). All environment knobs used anywhere in dynamo_tpu are declared
here with defaults, parsers, owning subsystem, and documentation; modules
read through the registry constants' ``.get()`` so the name, default, and
parser live in exactly one place. ``python -m dynamo_tpu.cli env`` prints
the registry (``--markdown`` emits the docs/design_docs/config_knobs.md
reference table), and dynlint DYN008 enforces closure both directions:
no ad-hoc ``os.environ`` read of a DYN_TPU_* name anywhere else, no
declared knob without a reader.

This module is loaded BY FILE PATH by the linter and must stay
dependency-free (stdlib only).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

_REGISTRY: Dict[str, "EnvVar"] = {}


@dataclass(frozen=True)
class EnvVar:
    name: str
    default: Any
    parser: Callable[[str], Any]
    doc: str
    subsystem: str = ""

    def get(self) -> Any:
        raw = os.environ.get(self.name)
        if raw is None:
            return self.default
        try:
            return self.parser(raw)
        except (ValueError, TypeError):
            return self.default


def _register(
    name: str, default: Any, parser: Callable[[str], Any], doc: str,
    subsystem: str,
) -> EnvVar:
    var = EnvVar(name, default, parser, doc, subsystem)
    _REGISTRY[name] = var
    return var


def _parse_bool(raw: str) -> bool:
    return raw.strip().lower() in ("1", "true", "yes", "on")


def env_str(name: str, default: str, doc: str = "", subsystem: str = "") -> EnvVar:
    return _REGISTRY.get(name) or _register(name, default, str, doc, subsystem)


def env_int(name: str, default: int, doc: str = "", subsystem: str = "") -> EnvVar:
    return _REGISTRY.get(name) or _register(name, default, int, doc, subsystem)


def env_float(name: str, default: float, doc: str = "", subsystem: str = "") -> EnvVar:
    return _REGISTRY.get(name) or _register(name, default, float, doc, subsystem)


def env_bool(name: str, default: bool, doc: str = "", subsystem: str = "") -> EnvVar:
    return _REGISTRY.get(name) or _register(name, default, _parse_bool, doc, subsystem)


def registry() -> Dict[str, EnvVar]:
    return dict(_REGISTRY)


def render_markdown() -> str:
    """The knob reference table (docs/design_docs/config_knobs.md body).

    Grouped by owning subsystem, sorted by name within; the checked-in
    doc is regenerated from this (``python -m dynamo_tpu.cli env
    --markdown``) and a tier-1 test pins doc == registry so they cannot
    drift.
    """
    lines = [
        "# Configuration knob reference",
        "",
        "Generated from `dynamo_tpu/config.py` — do not edit by hand.",
        "Regenerate with `python -m dynamo_tpu.cli env --markdown`.",
        "Every `DYN_TPU_*` environment read in the package goes through",
        "this registry (enforced by dynlint DYN008; see",
        "[static_analysis.md](static_analysis.md)).",
        "",
    ]
    by_subsystem: Dict[str, list] = {}
    for var in _REGISTRY.values():
        by_subsystem.setdefault(var.subsystem or "misc", []).append(var)
    for subsystem in sorted(by_subsystem):
        lines.append(f"## {subsystem}")
        lines.append("")
        lines.append("| Name | Default | Type | Description |")
        lines.append("|---|---|---|---|")
        for var in sorted(by_subsystem[subsystem], key=lambda v: v.name):
            ptype = getattr(var.parser, "__name__", "str")
            if ptype == "_parse_bool":
                ptype = "bool"
            default = repr(var.default)
            doc = " ".join(var.doc.split())
            lines.append(f"| `{var.name}` | `{default}` | {ptype} | {doc} |")
        lines.append("")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Canonical knobs (ref: environment_names.rs). DYN_TPU_* namespace.
# ---------------------------------------------------------------------------

NAMESPACE = env_str(
    "DYN_TPU_NAMESPACE", "dynamo", "Default namespace for components",
    subsystem="runtime",
)
REQUEST_PLANE = env_str(
    "DYN_TPU_REQUEST_PLANE", "tcp",
    "Request plane for cross-process serving: tcp|http|local",
    subsystem="runtime",
)
DISCOVERY = env_str(
    "DYN_TPU_DISCOVERY", "memory",
    "Discovery backend: memory|file|discd (addr via DYN_TPU_DISCOVERY_ADDR)",
    subsystem="runtime",
)
DISCOVERY_ADDR = env_str(
    "DYN_TPU_DISCOVERY_ADDR", "127.0.0.1:6180",
    "discd service address or file-backend directory",
    subsystem="runtime",
)
EVENT_PLANE = env_str(
    "DYN_TPU_EVENT_PLANE", "zmq", "Event plane: memory|zmq",
    subsystem="runtime",
)
EVENT_PLANE_ADDR = env_str(
    "DYN_TPU_EVENT_PLANE_ADDR",
    "127.0.0.1:6181:6182",
    "ZMQ event broker address host:xsub_port:xpub_port",
    subsystem="runtime",
)
TCP_HOST = env_str(
    "DYN_TPU_TCP_HOST", "127.0.0.1",
    "Advertised host for the TCP request plane",
    subsystem="runtime",
)
LEASE_TTL = env_float(
    "DYN_TPU_LEASE_TTL", 10.0, "Discovery lease TTL seconds",
    subsystem="runtime",
)
KV_QUANT_AUTO_CTX = env_int(
    "DYN_TPU_KV_QUANT_AUTO_CTX", 512,
    "kv_cache_dtype=auto: quantize the KV cache to int8 when max_model_len "
    "reaches this (measured v5e break-even: int8 KV loses ~3.6 ms/step at "
    "ctx<=160 from scale DMAs, wins beyond a few hundred tokens and "
    "doubles pool capacity)",
    subsystem="engine",
)
FLIGHT_DUMP_DIR = env_str(
    "DYN_TPU_FLIGHT_DUMP_DIR", "",
    "Directory for engine flight-recorder JSON dumps on tick abort "
    "(empty = system temp dir)",
    subsystem="engine",
)
KV_BLOCK_SIZE = env_int(
    "DYN_TPU_KV_BLOCK_SIZE", 16,
    "KV cache block size in tokens (the worker/mocker --block-size "
    "default)",
    subsystem="engine",
)
DECODE_BQ = env_int(
    "DYN_TPU_DECODE_BQ", 0,
    "Decode paged-attention kernel batch-block (BQ) override for shape "
    "tuning; 0 = auto (measured v5e: 16 for int8-quantized KV pools, 8 "
    "for bf16 — BQ bounded by the ~16 MB scoped VMEM the double-buffered "
    "page pairs occupy)",
    subsystem="ops",
)
LOG_LEVEL = env_str(
    "DYN_TPU_LOG", "info", "Log level (trace|debug|info|warn|error)",
    subsystem="logging",
)
LOG_JSON = env_bool(
    "DYN_TPU_LOG_JSON", False, "Emit JSONL structured logs",
    subsystem="logging",
)
HTTP_HOST = env_str(
    "DYN_TPU_HTTP_HOST", "0.0.0.0", "Frontend HTTP bind host",
    subsystem="frontend",
)
HTTP_PORT = env_int(
    "DYN_TPU_HTTP_PORT", 8000, "Frontend HTTP bind port",
    subsystem="frontend",
)
SYSTEM_PORT = env_int(
    "DYN_TPU_SYSTEM_PORT", 9090,
    "System status server port (/health /live /metrics)",
    subsystem="frontend",
)
ROUTER_TEMPERATURE = env_float(
    "DYN_TPU_ROUTER_TEMPERATURE", 0.0,
    "KV router softmax sampling temperature (0 = argmin)",
    subsystem="router",
)
ROUTER_OVERLAP_WEIGHT = env_float(
    "DYN_TPU_ROUTER_OVERLAP_WEIGHT", 1.0, "KV router overlap score weight",
    subsystem="router",
)
MIGRATION_LIMIT = env_int(
    "DYN_TPU_MIGRATION_LIMIT", 3,
    "Max per-request migrations to new workers on stream death",
    subsystem="llm",
)
MIGRATION_REPREFILL_CAP = env_int(
    "DYN_TPU_MIGRATION_REPREFILL_CAP", 131072,
    "Total re-prefill token budget across all migrations of one stream "
    "(caps the work a flapping worker set can burn per request)",
    subsystem="llm",
)
TOOL_JAIL_CAP_CHARS = env_int(
    "DYN_TPU_TOOL_JAIL_CAP_CHARS", 262144,
    "Tool-call jail unresolved-buffer cap (chars): generous for real "
    "calls, small enough that a marker bomb cannot balloon host RSS",
    subsystem="parsers",
)
# -- multi-host topology (parallel/multihost.py)
COORDINATOR = env_str(
    "DYN_TPU_COORDINATOR", "",
    "JAX multi-process coordinator address host:port (empty = single "
    "host; setting it opts the worker into the multihost env contract)",
    subsystem="parallel",
)
NUM_PROCESSES = env_int(
    "DYN_TPU_NUM_PROCESSES", 1,
    "Process count joining the multi-process JAX runtime",
    subsystem="parallel",
)
PROCESS_ID = env_int(
    "DYN_TPU_PROCESS_ID", 0,
    "This worker's process index in the multi-process JAX runtime",
    subsystem="parallel",
)
# -- disaggregated KV transfer (disagg/handlers.py)
PULL_ATTEMPTS = env_int(
    "DYN_TPU_PULL_ATTEMPTS", 3,
    "Bounded retry: attempts per decode-side KV pull (1 = single-shot)",
    subsystem="disagg",
)
PULL_BACKOFF_S = env_float(
    "DYN_TPU_PULL_BACKOFF_S", 0.05,
    "Exponential backoff base between pull attempts (base x 2^(n-1), "
    "capped)",
    subsystem="disagg",
)
PULL_TIMEOUT_S = env_float(
    "DYN_TPU_PULL_TIMEOUT_S", 30.0,
    "Per-attempt pull timeout when the request carries no deadline; with "
    "one, each attempt gets min(this, time remaining)",
    subsystem="disagg",
)
BREAKER_OPEN_AFTER = env_int(
    "DYN_TPU_BREAKER_OPEN_AFTER", 3,
    "Consecutive pull failures from one prefill source before the "
    "(src -> worker) circuit opens",
    subsystem="disagg",
)
BREAKER_COOLDOWN_S = env_float(
    "DYN_TPU_BREAKER_COOLDOWN_S", 30.0,
    "Open-circuit cooldown before the next pull is admitted as the "
    "half-open probe",
    subsystem="disagg",
)
KV_CHUNK_BYTES = env_int(
    "DYN_TPU_KV_CHUNK_BYTES", 8 << 20,
    "KV transfer chunk size: one message blocks the event loop and "
    "doubles peak host memory; ~8 MB chunks pipeline gather/wire/scatter",
    subsystem="disagg",
)
# -- overload armor (runtime/overload.py; docs/design_docs/overload_control.md)
OVERLOAD_MAX_CONCURRENCY = env_int(
    "DYN_TPU_OVERLOAD_MAX_CONCURRENCY", 256,
    "Frontend streams generating concurrently; excess queues (EDF)",
    subsystem="overload",
)
OVERLOAD_MAX_QUEUE = env_int(
    "DYN_TPU_OVERLOAD_MAX_QUEUE", 1024,
    "Bounded admission queue depth; beyond it requests shed 429",
    subsystem="overload",
)
OVERLOAD_MAX_QUEUE_DELAY_S = env_float(
    "DYN_TPU_OVERLOAD_MAX_QUEUE_DELAY_S", 30.0,
    "Shed when predicted queue delay exceeds this (429 + Retry-After)",
    subsystem="overload",
)
OVERLOAD_DEFAULT_DEADLINE_S = env_float(
    "DYN_TPU_OVERLOAD_DEFAULT_DEADLINE_S", 0.0,
    "Deadline stamped on requests that carry none (0 = unbounded)",
    subsystem="overload",
)
OVERLOAD_ITL_SLA_MS = env_float(
    "DYN_TPU_OVERLOAD_ITL_SLA_MS", 0.0,
    "p50 ITL SLA driving healthy->brownout->shed (0 = brownout disabled; "
    "admission caps still enforce)",
    subsystem="overload",
)
OVERLOAD_BROWNOUT_MAX_TOKENS = env_int(
    "DYN_TPU_OVERLOAD_BROWNOUT_MAX_TOKENS", 256,
    "max_tokens clamp applied while browned out",
    subsystem="overload",
)
# -- trajectory plane (runtime/trajectory.py; docs/design_docs/request_trajectory.md)
TRAJECTORY_RECENT = env_int(
    "DYN_TPU_TRAJECTORY_RECENT", 256,
    "Recent request trajectories retained for GET /debug/trajectory",
    subsystem="trajectory",
)
TRAJECTORY_SLOW = env_int(
    "DYN_TPU_TRAJECTORY_SLOW", 64,
    "Slow/errored trajectory summaries retained past recent-ring eviction",
    subsystem="trajectory",
)
TRAJECTORY_SHIP_INTERVAL_S = env_float(
    "DYN_TPU_TRAJECTORY_SHIP_S", 0.5,
    "Worker-side finished-span batch flush cadence onto the event plane",
    subsystem="trajectory",
)
SLO_TTFT_MS = env_float(
    "DYN_TPU_SLO_TTFT_MS", 0.0,
    "TTFT SLA for the goodput/burn-rate gauges (0 = SLO tracking off)",
    subsystem="trajectory",
)
SLO_ITL_MS = env_float(
    "DYN_TPU_SLO_ITL_MS", 0.0,
    "Mean-ITL SLA for the goodput/burn-rate gauges (0 = SLO tracking off)",
    subsystem="trajectory",
)
SLO_TARGET = env_float(
    "DYN_TPU_SLO_TARGET", 0.99,
    "SLO target the burn-rate denominates against (error budget = 1 - target)",
    subsystem="trajectory",
)
# -- crash plane (runtime/liveness.py; docs/design_docs/fault_tolerance.md)
LOAD_REPORT_INTERVAL_S = env_float(
    "DYN_TPU_LOAD_REPORT_INTERVAL_S", 1.0,
    "Worker load-report publish cadence (router/publisher.py "
    "LoadPublisher). The liveness detection budget is denominated in "
    "these intervals, so shrinking it tightens dead-worker detection",
    subsystem="liveness",
)
LIVENESS_INTERVAL_S = env_float(
    "DYN_TPU_LIVENESS_INTERVAL_S", 1.0,
    "Expected worker load-report cadence the frontend's liveness tracker "
    "judges missed intervals against (match the LoadPublisher interval)",
    subsystem="liveness",
)
LIVENESS_SUSPECT_AFTER = env_int(
    "DYN_TPU_LIVENESS_SUSPECT_AFTER", 2,
    "Missed load-report intervals before a worker is SUSPECT",
    subsystem="liveness",
)
LIVENESS_DEAD_AFTER = env_int(
    "DYN_TPU_LIVENESS_DEAD_AFTER", 5,
    "Missed load-report intervals before a worker is DEAD: drop_worker "
    "reconciliation runs and its in-flight streams abort into migration "
    "(detection-to-migration is bounded by dead_after x interval)",
    subsystem="liveness",
)
WORKER_ID = env_int(
    "DYN_TPU_WORKER_ID", 0,
    "Stable worker identity across restarts (0 = random per start). A "
    "restarted worker re-registers under the SAME id with a fresh "
    "incarnation so warm rejoin and incarnation fencing line up",
    subsystem="liveness",
)
GRACE_PERIOD = env_float(
    "DYN_TPU_GRACE_PERIOD", 30.0, "Graceful-shutdown drain seconds",
    subsystem="liveness",
)
DRAIN_DEADLINE_S = env_float(
    "DYN_TPU_DRAIN_DEADLINE_S", 30.0,
    "Live-handoff drain budget (SIGTERM / POST /drain / preStop): handoffs "
    "not completed by then fall back to re-prefill migration",
    subsystem="liveness",
)
DRAIN_HANDOFF_CONCURRENCY = env_int(
    "DYN_TPU_DRAIN_HANDOFF_CONCURRENCY", 4,
    "Concurrent handoff ships per drain: detach/export serialize at the "
    "engine's reconciled boundary, but the peer accept-ack round trips "
    "are independent — pipelining them keeps a full worker's drain "
    "inside the deadline on a slow link",
    subsystem="liveness",
)

# -- perf ledger (runtime/perf_ledger.py)
PERF_WINDOW = env_int(
    "DYN_TPU_PERF_WINDOW", 256,
    "Perf-ledger rolling window (samples per decode shape; bounds both "
    "memory and quantile cost)",
    subsystem="perf",
)
PERF_SAMPLE_TTL_S = env_float(
    "DYN_TPU_PERF_SAMPLE_TTL_S", 120.0,
    "Perf-ledger sample TTL in seconds (stale samples age out so the "
    "windows describe the CURRENT regime, not history)",
    subsystem="perf",
)
PERF_EVAL_INTERVAL_S = env_float(
    "DYN_TPU_PERF_EVAL_INTERVAL_S", 5.0,
    "Seconds between perf-sentinel evaluations (the fingerprint "
    "comparison runs at this cadence, not per tick)",
    subsystem="perf",
)
PERF_NOISE_BAND = env_float(
    "DYN_TPU_PERF_NOISE_BAND", 0.10,
    "Fractional noise band around a fingerprint before the sentinel "
    "calls regression (0.10 = ±5%% run-to-run noise stays silent, a "
    "20%% slowdown is flagged)",
    subsystem="perf",
)
PERF_MIN_SAMPLES = env_int(
    "DYN_TPU_PERF_MIN_SAMPLES", 16,
    "Samples a window needs before the sentinel issues a verdict for it",
    subsystem="perf",
)
PERF_FINGERPRINT_PATH = env_str(
    "DYN_TPU_PERF_FINGERPRINT_PATH", "",
    "Where steady-state perf fingerprints persist across restarts "
    "(JSON; empty = in-memory only, every start is a cold start)",
    subsystem="perf",
)
# -- request lifecycle plane (runtime/lifecycle.py)
SLOW_REQUEST_S = env_float(
    "DYN_TPU_SLOW_REQUEST_S", 30.0,
    "Requests slower than this (seconds, received→done) are retained in "
    "the slow-request capture ring",
    subsystem="lifecycle",
)
LIFECYCLE_RECENT = env_int(
    "DYN_TPU_LIFECYCLE_RECENT", 256,
    "Recent-request timelines retained for GET /debug/requests",
    subsystem="lifecycle",
)
LIFECYCLE_SLOW = env_int(
    "DYN_TPU_LIFECYCLE_SLOW", 64,
    "Slow-request timelines retained past recent-ring eviction",
    subsystem="lifecycle",
)
# -- KV reuse observability (runtime/kv_reuse_observe.py)
KV_SKETCH_CAPACITY = env_int(
    "DYN_TPU_KV_SKETCH_CAPACITY", 4096,
    "Prefix-popularity sketch capacity (tracked prefixes; space-saving "
    "min-replacement keeps memory bounded regardless of distinct "
    "prefixes)",
    subsystem="kv-reuse",
)
KV_SKETCH_HALF_LIFE_S = env_float(
    "DYN_TPU_KV_SKETCH_HALF_LIFE_S", 600.0,
    "Popularity decay half-life in seconds (recency weighting of the "
    "prefix sketch; 0 disables decay)",
    subsystem="kv-reuse",
)
# -- auditing / tracing / native seams
AUDIT_POLICY = env_str(
    "DYN_TPU_AUDIT", "off",
    "Request auditing: off | stderr | file:<path> (JSONL records)",
    subsystem="frontend",
)
NATIVE = env_bool(
    "DYN_TPU_NATIVE", True,
    "Use C++ native components when buildable (0 = pure-Python fallbacks)",
    subsystem="native",
)
TRACE_FILE = env_str(
    "DYN_TPU_TRACE_FILE", "",
    "Append finished spans as JSONL to this path ('' disables file "
    "export)",
    subsystem="tracing",
)
OTLP_ENDPOINT = env_str(
    "DYN_TPU_OTLP_ENDPOINT", "",
    "OTLP/HTTP traces endpoint (e.g. http://collector:4318/v1/traces); "
    "'' disables the wire exporter",
    subsystem="tracing",
)
OTLP_SERVICE = env_str(
    "DYN_TPU_OTLP_SERVICE", "dynamo-tpu",
    "service.name resource attribute on exported spans",
    subsystem="tracing",
)

# The closed set dynlint DYN008 checks both directions: every DYN_TPU_*
# env read in the package resolves to one of these, every entry has a
# reader. Declarations above register in order, so this tuple is total
# by construction — subsystem modules alias these constants
# (``PERF_WINDOW = config.PERF_WINDOW``) instead of registering their
# own, so `dynamo-tpu env` and the generated reference table see the
# whole namespace without importing the serving stack.
ALL_KNOBS = tuple(_REGISTRY.values())
