"""Native JAX engine worker component (python -m dynamo_tpu.worker).

Reference parity: components/src/dynamo/vllm/main.py — the engine worker
process: boot the engine, register the model card, serve the endpoint,
publish KV events and load stats. The engine here is the first-party JAX
engine instead of vLLM.
"""
