from __future__ import annotations

import argparse
import asyncio
import os
import random
import signal

import jax

from dynamo_tpu import config
from dynamo_tpu.engines.tpu import JaxEngine, JaxEngineArgs
from dynamo_tpu.llm.discovery import register_llm
from dynamo_tpu.llm.model_card import ModelDeploymentCard, RuntimeConfig
from dynamo_tpu.models.config import (
    ModelConfig,
    gemma2_2b_config,
    gemma3_1b_config,
    llama3_3b_config,
    llama3_8b_config,
    llama3_70b_config,
    mixtral_8x7b_config,
    qwen2_500m_config,
    qwen3_8b_config,
    tiny_config,
)
from dynamo_tpu.parallel import MeshConfig, make_mesh
from dynamo_tpu.router import KvEventPublisher, LoadPublisher
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.utils.logging import configure_logging, get_logger

logger = get_logger(__name__)

BUILTIN_CONFIGS = {
    "tiny": tiny_config,
    "qwen2.5-0.5b": qwen2_500m_config,
    "llama-3-8b": llama3_8b_config,
    "llama-3.2-3b": llama3_3b_config,
    "qwen3-8b": qwen3_8b_config,
    "llama-3-70b": llama3_70b_config,
    "gemma-2-2b": gemma2_2b_config,
    "gemma-3-1b": gemma3_1b_config,
    "mixtral-8x7b": mixtral_8x7b_config,
}


def build_parser() -> argparse.ArgumentParser:
    """The worker's argument surface. Factored out so recipe validation
    (tests/test_recipes.py, tests/test_70b_fit.py) resolves the SAME
    defaults a deployed worker gets."""
    parser = argparse.ArgumentParser("dynamo-tpu worker (native JAX engine)")
    parser.add_argument(
        "--model",
        default="tiny",
        help="HF model directory, or a builtin config name "
        f"({', '.join(BUILTIN_CONFIGS)}) with random weights",
    )
    parser.add_argument("--served-model-name", default=None)
    parser.add_argument("--namespace", default=config.NAMESPACE.get())
    parser.add_argument("--component", default="backend")
    parser.add_argument("--endpoint", default="generate")
    parser.add_argument(
        "--block-size", type=int, default=config.KV_BLOCK_SIZE.get()
    )
    parser.add_argument("--num-kv-blocks", type=int, default=2048)
    parser.add_argument("--max-num-seqs", type=int, default=16)
    parser.add_argument("--max-model-len", type=int, default=2048)
    parser.add_argument("--prefill-chunk", type=int, default=512)
    parser.add_argument("--tensor-parallel-size", "--tp", type=int, default=1)
    parser.add_argument("--no-prefix-caching", action="store_true")
    parser.add_argument(
        "--is-prefill-worker", action="store_true",
        help="serve disaggregated prefill (ref: vllm/args.py --is-prefill-worker)",
    )
    parser.add_argument(
        "--prefill-component", default="prefill",
        help="component name prefill workers register under",
    )
    parser.add_argument(
        "--kv-offload-blocks", type=int, default=0,
        help="host-RAM KV tier capacity in blocks (0 = offload disabled; "
        "ref: KVBM G2 tier)",
    )
    parser.add_argument(
        "--kv-offload-dir", default=None,
        help="disk KV tier spool directory (KVBM G3; requires --kv-offload-blocks)",
    )
    parser.add_argument(
        "--kv-remote", default=None, metavar="NS/COMPONENT/ENDPOINT",
        help="shared KV store endpoint (KVBM G4; run python -m dynamo_tpu.kvbm)",
    )
    parser.add_argument(
        "--kv-host-arena-mb", type=int, default=0,
        help="back the host KV tier with a preallocated arena of this many "
        "MB (0 = plain numpy blocks)",
    )
    parser.add_argument("--decode-steps", type=int, default=8,
                        help="fused decode iterations per device dispatch")
    parser.add_argument("--pipeline-depth", type=int, default=2,
                        help="decode bursts in flight on the device (2 = "
                        "double-buffered dispatch/reap, 1 = synchronous; "
                        "docs/design_docs/decode_pipelining.md)")
    parser.add_argument("--tick-budget", action="store_true",
                        help="intra-chip prefill/decode disaggregation: cap "
                        "per-tick prefill chunk tokens with the closed-loop "
                        "TickBudgeter (docs/design_docs/disagg_serving.md, "
                        "intra-chip middle mode)")
    parser.add_argument("--tick-budget-floor", type=int, default=None,
                        help="starvation floor in prefill tokens per tick "
                        "(default: one prefill chunk)")
    parser.add_argument("--tick-budget-ceiling", type=int, default=None,
                        help="budget ceiling in prefill tokens per tick "
                        "(default: admit_batches_per_tick x prefill_chunk — "
                        "the unbudgeted per-tick admission cap)")
    parser.add_argument("--tick-budget-policy", type=float, default=0.5,
                        help="0 = strict-ITL (start at the floor), 1 = "
                        "max-throughput (start at the ceiling)")
    parser.add_argument("--tick-budget-itl-slo-ms", type=float, default=None,
                        help="per-token ITL SLO driving the budget's "
                        "shrink/grow control law (off: budget only moves "
                        "via the overload ladder's squeeze rung)")
    parser.add_argument("--lora-dir", default=None,
                        help="directory of PEFT LoRA adapters to serve "
                        "(ref: lib/llm/src/lora.rs)")
    parser.add_argument("--weight-cache-dir", default=None,
                        help="fast-restart weight cache (GMS-role, "
                        "models/weight_cache.py); default ~/.cache/dynamo_tpu")
    parser.add_argument("--system-port", type=int, default=None,
                        help="per-worker system HTTP server port "
                        "(health/metrics/engine admin/LoRAs; 0 = ephemeral; "
                        "ref: system_status_server.rs)")
    parser.add_argument("--model-type", choices=["chat", "completion", "multimodal"],
                        default="chat",
                        help="model card type; 'multimodal' makes the "
                        "frontend splice encode-worker embeddings (E/P/D)")
    parser.add_argument("--speculative", choices=["ngram"], default=None,
                        help="speculative decoding: ngram = prompt-lookup "
                        "proposals verified in one dispatch (greedy only)")
    parser.add_argument("--spec-k", type=int, default=4,
                        help="proposed tokens per speculative verify step")
    parser.add_argument("--spec-ngram", type=int, default=3,
                        help="match length for prompt-lookup proposals")
    parser.add_argument("--kv-checkpoint-dir", default=None,
                        help="warm-cache checkpoint directory (chrek/CRIU "
                        "role): restored at startup when present, saved on "
                        "graceful shutdown")
    parser.add_argument("--quantization", choices=["int8"], default=None,
                        help="weight-only quantization (int8: per-channel, "
                        "halves weight HBM — the FP8-checkpoint deployment "
                        "lever, TPU-style)")
    parser.add_argument("--kv-cache-dtype", choices=["int8", "auto"],
                        default=None,
                        help="KV-cache quantization (int8: per-token-head "
                        "dynamic scales — 2x KV capacity and half the "
                        "history-read bytes; the kv_cache_dtype=fp8 engine "
                        "lever, TPU-style). 'auto' applies the measured "
                        "break-even policy: int8 when max_model_len >= "
                        "DYN_TPU_KV_QUANT_AUTO_CTX or the pool cannot hold "
                        "the worst case at bf16")
    parser.add_argument("--coordinator", default=None,
                        help="multi-host: host:port of rank 0's "
                        "jax.distributed coordinator (or env "
                        "DYN_TPU_COORDINATOR); one process per host forms "
                        "ONE logical worker, rank 0 serves the endpoint")
    parser.add_argument("--num-processes", type=int, default=None,
                        help="multi-host world size (env DYN_TPU_NUM_PROCESSES)")
    parser.add_argument("--process-id", type=int, default=None,
                        help="multi-host rank of this process (env "
                        "DYN_TPU_PROCESS_ID)")
    return parser


async def main() -> None:
    parser = build_parser()
    args = parser.parse_args()
    if args.is_prefill_worker and args.component == "backend":
        args.component = args.prefill_component
    if args.kv_offload_blocks <= 0 and (
        args.kv_remote or args.kv_host_arena_mb or args.kv_offload_dir
    ):
        parser.error(
            "--kv-remote/--kv-host-arena-mb/--kv-offload-dir require "
            "--kv-offload-blocks > 0 (they configure the offload tier stack)"
        )
    if args.kv_remote:
        kv_remote_parts = args.kv_remote.split("/")
        if len(kv_remote_parts) != 3 or not all(kv_remote_parts):
            parser.error(
                f"--kv-remote must be NS/COMPONENT/ENDPOINT, got {args.kv_remote!r}"
            )

    configure_logging()

    # Multi-host: join the jax.distributed runtime BEFORE any JAX use (the
    # backend must not exist yet). One process per host; rank 0 is the
    # leader and the only rank that serves/registers the endpoint (ref DP
    # leader pattern, components/src/dynamo/vllm/main.py:67-78).
    from dynamo_tpu.parallel.multihost import init_multihost

    topo = init_multihost(args.coordinator, args.num_processes, args.process_id)

    runtime = DistributedRuntime.from_settings() if topo.is_leader else None

    model_path = None
    if args.model in BUILTIN_CONFIGS:
        model_config = BUILTIN_CONFIGS[args.model]()
        params = None  # random init inside the engine
    else:
        model_path = args.model
        model_config = ModelConfig.from_model_dir(args.model)
        from dynamo_tpu.models.weight_cache import (
            DEFAULT_CACHE_DIR,
            load_checkpoint_cached,
        )

        params, cache_hit = load_checkpoint_cached(
            args.model, model_config,
            cache_dir=args.weight_cache_dir or DEFAULT_CACHE_DIR,
            quantization=args.quantization,
        )
        print(f"weights loaded (cache {'hit' if cache_hit else 'miss'})", flush=True)

    mesh = None
    if topo.is_multihost:
        # The global mesh spans every process's devices. Default tp = the
        # largest device-count divisor the model's kv heads can shard over
        # (a NamedSharding with more partitions than the axis size fails at
        # device_put); leftover devices become data parallelism.
        n_dev = len(jax.devices())
        if args.tensor_parallel_size > 1:
            tp = args.tensor_parallel_size
        else:
            tp = 1
            while (
                tp * 2 <= n_dev
                and n_dev % (tp * 2) == 0
                and model_config.n_kv_heads % (tp * 2) == 0
            ):
                tp *= 2
        mesh = make_mesh(MeshConfig(tp=tp, dp=n_dev // tp), jax.devices())
    elif args.tensor_parallel_size > 1:
        mesh = make_mesh(
            MeshConfig(tp=args.tensor_parallel_size), jax.devices()
        )

    engine_args = JaxEngineArgs(
        config=model_config,
        block_size=args.block_size,
        num_kv_blocks=args.num_kv_blocks,
        max_num_seqs=args.max_num_seqs,
        max_model_len=args.max_model_len,
        prefill_chunk=args.prefill_chunk,
        enable_prefix_caching=not args.no_prefix_caching,
        decode_steps=args.decode_steps,
        pipeline_depth=args.pipeline_depth,
        lora_dir=args.lora_dir,
        spec_mode=args.speculative,
        spec_k=args.spec_k,
        spec_ngram=args.spec_ngram,
        quantization=args.quantization,
        kv_cache_dtype=args.kv_cache_dtype,
        tick_budget_enabled=args.tick_budget,
        tick_budget_floor_tokens=args.tick_budget_floor,
        tick_budget_ceiling_tokens=args.tick_budget_ceiling,
        tick_budget_policy=args.tick_budget_policy,
        tick_budget_itl_slo_s=(
            args.tick_budget_itl_slo_ms / 1000.0
            if args.tick_budget_itl_slo_ms
            else None
        ),
    )

    if topo.is_multihost:
        from dynamo_tpu.engines.tpu import spmd
        from dynamo_tpu.engines.tpu.runner import DeviceRunner
        from dynamo_tpu.parallel.multihost import spmd_port

        runner = DeviceRunner(engine_args, params, mesh=mesh, topology=topo)
        port = spmd_port(topo.coordinator)
        if not topo.is_leader:
            # Follower rank: contribute devices to the collectives and
            # replay the leader's op stream until it closes the channel.
            host = topo.coordinator.rsplit(":", 1)[0]
            spmd.follow(runner, spmd.make_follower(host, port))
            return
        bcast = spmd.make_broadcaster(
            port, num_followers=topo.num_processes - 1
        )
        runner.set_broadcaster(bcast)
    else:
        runner = None

    name = args.served_model_name or model_config.name
    # Stable worker identity (crash plane): a restarted worker re-registers
    # under the SAME id with a fresh process incarnation, so the router's
    # rejoin purge and the fence line up; 0 keeps the old random-per-start
    # behavior for ad-hoc workers.
    instance_id = config.WORKER_ID.get() or random.getrandbits(63)
    # Trajectory plane: label this process's spans (clock-domain tag for
    # cross-worker stitching) and ship finished spans frontend-ward.
    from dynamo_tpu.runtime.trajectory import (
        TrajectoryShipper,
        set_global_shipper,
    )
    from dynamo_tpu.utils.tracing import global_tracer, set_service

    set_service(f"worker-{instance_id:#x}")
    trajectory_shipper = TrajectoryShipper(
        runtime.event_plane, args.namespace
    )
    trajectory_shipper.attach(global_tracer())
    set_global_shipper(trajectory_shipper)
    # Eagerly attach the local store too: the worker's own
    # /debug/trajectory must show ITS slice from the first request, not
    # from whenever the route is first scraped.
    from dynamo_tpu.runtime.trajectory import global_store

    global_store()
    kv_pub = KvEventPublisher(
        runtime.event_plane, args.namespace, args.component, instance_id
    )
    engine = JaxEngine(
        engine_args,
        params,
        mesh=mesh,
        on_kv_event=kv_pub.on_kv_event,
        runner=runner,
    )
    # Answer router re-sync requests with the pool's committed set (the
    # JetStream replay role) — a restarted router rebuilds its radix index
    # immediately instead of waiting for TTL churn.
    kv_pub.set_snapshot_fn(engine.pool.committed_view)
    kvbm = None
    if args.kv_offload_blocks > 0:
        from dynamo_tpu.kvbm import DiskTier, HostTier, RemoteTier, TieredKvManager

        disk = DiskTier(args.kv_offload_dir) if args.kv_offload_dir else None
        remote = None
        if args.kv_remote:
            ns, comp, ep_name = kv_remote_parts

            async def _kv_client():
                return await (
                    runtime.namespace(ns).component(comp).endpoint(ep_name).client()
                )

            remote = RemoteTier(_kv_client)
        kvbm = TieredKvManager(
            HostTier(
                args.kv_offload_blocks, next_tier=disk,
                arena_bytes=args.kv_host_arena_mb * (1 << 20) or None,
            ),
            remote=remote,
        )
        kvbm.attach(engine)
    load_pub = LoadPublisher(
        runtime.event_plane, args.namespace, args.component, instance_id,
        engine.stats, total_blocks=args.num_kv_blocks,
    )

    card = ModelDeploymentCard(
        name=name,
        model_type=args.model_type,
        model_path=model_path,
        context_length=args.max_model_len,
        kv_block_size=args.block_size,
        eos_token_ids=list(model_config.eos_token_ids),
        runtime_config=RuntimeConfig(
            total_kv_blocks=args.num_kv_blocks,
            kv_block_size=args.block_size,
            max_num_seqs=args.max_num_seqs,
            max_context_len=args.max_model_len,
        ),
    )
    from dynamo_tpu.disagg import DecodeHandler, KvTransferHandler, PrefillHandler
    from dynamo_tpu.runtime.liveness import process_incarnation

    component = runtime.namespace(args.namespace).component(args.component)
    endpoint = component.endpoint(args.endpoint)
    kv_endpoint = component.endpoint("kv")

    # Crash-plane startup order (docs/design_docs/fault_tolerance.md):
    # 1. system server UP first — /healthz (liveness: the process turns)
    #    answers during a long restore while /readyz stays 503, so the
    #    kubelet neither restarts the pod nor routes traffic at it;
    # 2. engine start + warm KV checkpoint restore (never-raise: any
    #    stamp mismatch or corruption is a logged, counted cold start);
    # 3. endpoints served + model registered under the FRESH incarnation —
    #    only now does the fleet see the worker at all;
    # 4. load reports begin (incarnation-stamped) and readiness flips —
    #    restored prefixes re-advertise via the router's kv-sync snapshot
    #    pull the moment the registration lands.
    ready_state: dict = {"ready": False, "detail": "starting"}
    system_server = None
    if args.system_port is not None:
        from dynamo_tpu.runtime.system_server import (
            SystemStatusServer,
            attach_engine,
        )

        system_server = SystemStatusServer(port=args.system_port)
        attach_engine(system_server, engine)

        def _worker_ready():
            # Drain-aware through EVERY trigger path (signal, POST /drain,
            # preStop GET): a draining worker is alive but not ready.
            dc = ready_state.get("drain_controller")
            if dc is not None and dc.state != 0:
                return False, "draining"
            return ready_state["ready"], ready_state["detail"]

        system_server.register_readiness("worker", _worker_ready)
        if kvbm is not None:
            kvbm.register_metrics(system_server)
        await system_server.start()
        print(f"system server on :{system_server.port}", flush=True)

    ready_state["detail"] = "starting engine"
    await engine.start()
    if args.kv_checkpoint_dir:
        # Restore BEFORE registering: the model card and the first load
        # report must describe a worker whose warm cache is already
        # installed, so a shared-prefix request routed here on the first
        # report serves without re-prefill. load_checkpoint never raises —
        # a bad checkpoint is a counted cold start, not a crash loop.
        ready_state["detail"] = "restoring KV checkpoint"
        n = await engine.load_checkpoint(args.kv_checkpoint_dir)
        if n:
            print(f"restored {n} warm KV blocks", flush=True)

    ready_state["detail"] = "registering endpoints"
    incarnation = process_incarnation()
    served_kv = await kv_endpoint.serve_endpoint(
        KvTransferHandler(engine).generate, instance_id=instance_id
    )

    async def control(request, context):
        """Admin ops (ref: clear_kv_blocks.rs; fanned out by the frontend)."""
        op = request.get("op") if isinstance(request, dict) else None
        if op == "clear_kv_blocks":
            yield {"cleared": engine.clear_kv_blocks()}
        elif op == "stats":
            yield engine.stats()
        else:
            yield {"error": f"unknown control op {op!r}"}

    served_ctl = await component.endpoint("control").serve_endpoint(
        control, instance_id=instance_id
    )
    served_handoff = None
    handoff_client_factory = None
    if args.is_prefill_worker:
        handler = PrefillHandler(engine, instance_id)
        served = await endpoint.serve_endpoint(
            handler.generate, instance_id=instance_id,
            metadata={"incarnation": incarnation},
        )
        # Prefill workers are found via their component endpoint, not the
        # model registry (ref: prefill_router.rs activate). Their in-flight
        # work is one bounded prefill each, so drain skips the handoff rung
        # (typed requeue re-dispatches whole requests).
    else:
        async def _kv_client():
            return await (
                runtime.namespace(args.namespace)
                .component(args.prefill_component)
                .endpoint("kv")
                .client()
            )

        handler = DecodeHandler(
            engine, kv_client_factory=_kv_client, worker_id=instance_id
        )
        # Load reports carry this worker's measured per-src pull bandwidth
        # (link-cost placement) and its open pull breakers (a FAILING link
        # is priced out of placement, not just a slow one).
        load_pub.link_bandwidth_fn = handler.link_bandwidth
        load_pub.link_faults_fn = handler.open_breaker_srcs
        served = await endpoint.serve_endpoint(
            handler.generate, instance_id=instance_id,
            metadata={"incarnation": incarnation},
        )
        await register_llm(
            runtime, card, endpoint, instance_id, incarnation=incarnation
        )
        # Live-handoff plane (rolling restarts): serve adoptions from
        # draining peers, and reach peers' handoff endpoints when WE drain.
        from dynamo_tpu.disagg import HANDOFF_ENDPOINT, HandoffHandler

        served_handoff = await component.endpoint(HANDOFF_ENDPOINT).serve_endpoint(
            HandoffHandler(engine).generate, instance_id=instance_id
        )

        async def handoff_client_factory():
            return await (
                runtime.namespace(args.namespace)
                .component(args.component)
                .endpoint(HANDOFF_ENDPOINT)
                .client()
            )
    load_pub.start()
    trajectory_shipper.start()
    # Worker-side overload plane: KV-pool-occupancy-driven brownout that
    # suspends speculative decode before admission backpressure turns
    # into a preemption storm (the engine's admit_kv_high_watermark does
    # the refusing; this re-arms spec when pressure clears). The
    # evaluate cadence rides the load-report task below.
    from dynamo_tpu.runtime.overload import OverloadController, config_from_env

    overload = OverloadController(
        config_from_env(),
        occupancy_source=lambda: engine.pool.usage,
    )
    overload.on_transition(
        lambda _old, new: engine.set_spec_suspended(new > 0)
    )
    if getattr(engine, "_budgeter", None) is not None:
        # Budget-squeeze rung: registering the lever makes the ladder
        # shrink the per-tick prefill budget one filled breach streak
        # BEFORE the max_tokens clamp, and release it last on recovery.
        # Unregistered (budgeter off), the ladder behaves exactly as
        # before.
        overload.on_budget_pressure(engine.set_budget_pressure)

    async def overload_eval_loop() -> None:
        while True:
            await asyncio.sleep(load_pub.interval_s)
            overload.evaluate()

    overload_task = asyncio.get_running_loop().create_task(
        overload_eval_loop(), name="overload-eval"
    )
    # Drain plane: SIGTERM (k8s pod deletion), POST /drain, or the preStop
    # hook triggers a live-handoff drain; the worker exits once drained.
    from dynamo_tpu.runtime.drain import DrainController

    shutdown = asyncio.Event()
    ready_state["drain_controller"] = drain_controller = DrainController(
        engine,
        worker_id=instance_id,
        handoff_client_factory=handoff_client_factory,
        load_publisher=load_pub,
        checkpoint_dir=args.kv_checkpoint_dir,
        on_drained=shutdown.set,
    )

    loop = asyncio.get_running_loop()

    def start_drain(sig_name: str) -> None:
        if drain_controller.state == 0:
            print(f"{sig_name}: draining (live handoff)...", flush=True)
        # A draining worker is alive but no longer ready: /readyz flips
        # 503 so the kubelet pulls it from service while streams hand off.
        ready_state["ready"] = False
        ready_state["detail"] = "draining"
        drain_controller.trigger()

    sigint_count = 0

    def on_sigint() -> None:
        nonlocal sigint_count
        sigint_count += 1
        if sigint_count >= 2:
            # Second ^C: the operator means NOW. Skip every drain step.
            print("second SIGINT: forcing exit", flush=True)
            os._exit(130)
        start_drain("SIGINT")

    # Loop signal handlers, NOT signal.signal: the previous bare
    # `asyncio.Event().wait()` meant SIGTERM killed the process without
    # ever running the finally block — no KV checkpoint, no graceful
    # endpoint shutdown, every live stream dropped.
    loop.add_signal_handler(signal.SIGTERM, start_drain, "SIGTERM")
    loop.add_signal_handler(signal.SIGINT, on_sigint)
    if system_server is not None:
        # Late source registration is fine: the server's routes consult
        # the registries per request (the server itself started before
        # the restore so /healthz was up the whole time).
        overload.register_metrics(system_server)
        drain_controller.register_metrics(system_server)
        system_server.register_drain(
            drain_controller.drain, drain_controller.status
        )
        if hasattr(handler, "register_metrics"):
            # DecodeHandler exposes the disagg transfer families; the
            # prefill handler has nothing to add.
            handler.register_metrics(system_server)
    ready_state["ready"] = True
    ready_state["detail"] = f"serving (incarnation {incarnation:#x})"
    print(
        f"worker serving {name} as {args.namespace}/{args.component}/"
        f"{args.endpoint} instance {instance_id:#x} "
        f"incarnation {incarnation:#x}",
        flush=True,
    )
    try:
        await shutdown.wait()
    finally:
        if (
            args.kv_checkpoint_dir
            and engine.pool.cached_blocks > 0
            and not drain_controller.checkpointed
        ):
            # Guarded: a drained/slept worker must not clobber a previous
            # warm checkpoint with an empty one.
            try:
                await engine.save_checkpoint(args.kv_checkpoint_dir)
            except Exception as exc:
                # Shutdown best-effort; next start just runs cold — but a
                # persistently failing checkpoint dir should be findable.
                logger.warning(
                    "KV checkpoint save failed on shutdown "
                    "(next start runs cold): %s", exc,
                )
        if system_server is not None:
            await system_server.stop()
        overload_task.cancel()
        from dynamo_tpu.runtime.tasks import reap_task

        await reap_task(overload_task, "overload eval loop", logger)
        if kvbm is not None:
            await kvbm.close()
        set_global_shipper(None)
        await trajectory_shipper.close()
        await load_pub.close()
        await kv_pub.close()
        await served.shutdown(grace_period=config.GRACE_PERIOD.get())
        await served_ctl.shutdown(grace_period=5)
        await served_kv.shutdown(grace_period=5)
        if served_handoff is not None:
            await served_handoff.shutdown(grace_period=5)
        await engine.stop()
        await runtime.shutdown(grace_period=config.GRACE_PERIOD.get())


if __name__ == "__main__":
    asyncio.run(main())
