"""Operator leader election over coordination.k8s.io/v1 Leases.

Reference parity: the reference operator runs controller-runtime's
lease-based leader election (deploy/operator/cmd/main.go:136-175,
--leader-elect) so replicated operator pods never double-reconcile. Same
contract here: one Lease object per election id; the holder renews
renewTime every renew_interval; a candidate takes over when the lease is
older than lease_duration (crashed holder) or absent.
"""

from __future__ import annotations

import asyncio
import time
import uuid
from typing import Any, Optional, Tuple

from dynamo_tpu.deploy.k8s_client import KubeApiError
from dynamo_tpu.runtime.tasks import reap_task
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

GROUP = "coordination.k8s.io"
VERSION = "v1"
PLURAL = "leases"


def _now_rfc3339() -> str:
    t = time.time()
    base = time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(t))
    return f"{base}.{int((t % 1) * 1e6):06d}Z"


def _parse_rfc3339(s: str) -> float:
    """Kept for observability/tooling: takeover no longer compares parsed
    remote timestamps against the local clock (see try_acquire_once)."""
    import calendar

    s = s.rstrip("Z")
    frac = 0.0
    if "." in s:
        s, f = s.split(".", 1)
        frac = float(f"0.{f}") if f else 0.0
    return calendar.timegm(time.strptime(s, "%Y-%m-%dT%H:%M:%S")) + frac


class LeaderElector:
    """Lease-based election: call start(); gate work on ``is_leader`` (or
    ``await wait_leader()``). Crash-safety comes from the lease going stale,
    not from graceful release — though stop() does release when possible."""

    def __init__(
        self,
        client: Any,  # deploy.k8s_client.KubeClient
        *,
        name: str = "dynamo-tpu-operator",
        k8s_namespace: str = "default",
        identity: Optional[str] = None,
        lease_duration_s: float = 15.0,
        renew_interval_s: Optional[float] = None,
    ) -> None:
        self._last_renew_ok = 0.0  # monotonic time of last successful renew
        self.client = client
        self.name = name
        self.k8s_namespace = k8s_namespace
        self.identity = identity or f"{name}-{uuid.uuid4().hex[:8]}"
        self.lease_duration_s = lease_duration_s
        self.renew_interval_s = renew_interval_s or lease_duration_s / 3.0
        self.is_leader = False
        self.transitions = 0  # acquired-count (observability/tests)
        # Staleness is judged by LOCAL observation, never by comparing our
        # wall clock against the remote holder's renewTime (client-go
        # leaderelection semantics): record what (holder, renewTime) we
        # last SAW and our local monotonic time when it last CHANGED. A
        # live holder on a skewed clock keeps changing renewTime, so the
        # observation timer keeps resetting and the lease is never stolen.
        self._observed: Optional[Tuple[Any, Any]] = None
        self._observed_changed_at = 0.0
        self._task: Optional[asyncio.Task] = None
        self._leader_event = asyncio.Event()
        self._stop = asyncio.Event()

    async def wait_leader(self, timeout: Optional[float] = None) -> bool:
        try:
            await asyncio.wait_for(self._leader_event.wait(), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def try_acquire_once(self) -> bool:
        """One acquire/renew attempt; updates is_leader."""
        spec_patch = {
            "spec": {
                "holderIdentity": self.identity,
                "leaseDurationSeconds": int(self.lease_duration_s),
                "renewTime": _now_rfc3339(),
            }
        }
        try:
            lease = await self.client.get(
                GROUP, VERSION, self.k8s_namespace, PLURAL, self.name
            )
        except KubeApiError as exc:
            if exc.status != 404:
                raise
            body = {
                "apiVersion": f"{GROUP}/{VERSION}",
                "kind": "Lease",
                "metadata": {"name": self.name},
                **spec_patch,
            }
            try:
                await self.client.create(
                    GROUP, VERSION, self.k8s_namespace, PLURAL, body
                )
                self._become(True)
                return True
            except KubeApiError as exc2:
                if exc2.status == 409:  # lost the create race
                    self._become(False)
                    return False
                raise
        spec = lease.get("spec") or {}
        holder = spec.get("holderIdentity")
        renew = spec.get("renewTime")
        duration = float(spec.get("leaseDurationSeconds") or self.lease_duration_s)
        # Local-observation staleness (client-go semantics): restart the
        # clock whenever the observed (holder, renewTime) pair changes, and
        # only call the lease stale once it has sat UNCHANGED for a full
        # lease duration of OUR monotonic time. Comparing time.time()
        # against the remote renewTime would let cross-machine clock skew
        # greater than lease_duration − renew_interval steal a LIVE lease
        # (split-brain: two operators reconciling at once).
        now_mono = time.monotonic()
        observed = (holder, renew)
        if observed != self._observed:
            self._observed = observed
            self._observed_changed_at = now_mono
        stale = (now_mono - self._observed_changed_at) > duration
        if holder == self.identity or not holder or stale:
            # renew, first claim, or takeover of a stale (crashed) holder.
            # The patch carries the observed resourceVersion: a concurrent
            # candidate's patch bumps it, so the second writer gets 409
            # instead of silently stealing the claim (split-brain guard —
            # the role of client-go leaderelection's update-with-RV).
            rv = (lease.get("metadata") or {}).get("resourceVersion")
            body = dict(spec_patch)
            if rv is not None:
                body["metadata"] = {"resourceVersion": str(rv)}
            try:
                await self.client.patch(
                    GROUP, VERSION, self.k8s_namespace, PLURAL, self.name,
                    body,
                )
            except KubeApiError as exc:
                if exc.status == 409:  # lost the takeover race
                    self._become(False)
                    return False
                raise
            self._become(True)
            return True
        self._become(False)
        return False

    def _become(self, leader: bool) -> None:
        if leader:
            self._last_renew_ok = time.monotonic()
        if leader and not self.is_leader:
            self.transitions += 1
            logger.info("leader election %s: ACQUIRED by %s", self.name, self.identity)
            self._leader_event.set()
        elif not leader and self.is_leader:
            logger.warning("leader election %s: LOST by %s", self.name, self.identity)
            self._leader_event.clear()
        self.is_leader = leader

    async def _run(self) -> None:
        while not self._stop.is_set():
            try:
                await self.try_acquire_once()
            except Exception:
                # apiserver hiccups: a leader keeps working until the lease
                # WOULD have gone stale — past that point a standby may
                # legitimately hold it, so this instance must demote
                # (client-go's renew deadline semantics) rather than
                # double-reconcile.
                logger.exception("leader election attempt failed")
                if (
                    self.is_leader
                    and time.monotonic() - self._last_renew_ok
                    > self.lease_duration_s
                ):
                    logger.warning(
                        "leader election %s: renew deadline exceeded — "
                        "demoting %s", self.name, self.identity,
                    )
                    self._become(False)
            try:
                await asyncio.wait_for(
                    self._stop.wait(), timeout=self.renew_interval_s
                )
            except asyncio.TimeoutError:
                pass

    def start(self) -> None:
        self._stop.clear()
        self._task = asyncio.get_running_loop().create_task(
            self._run(), name=f"leader-{self.name}"
        )

    async def stop(self) -> None:
        self._stop.set()
        if self._task is not None:
            self._task.cancel()
            await reap_task(self._task, f"leader-{self.name} loop", logger)
            self._task = None
        if self.is_leader:
            # Graceful release: zero the holder so a peer takes over at its
            # next tick instead of waiting out the lease duration. Guarded:
            # re-read the lease and release ONLY while we are still the
            # recorded holder, carrying the observed resourceVersion so a
            # concurrent renew/takeover turns our release into a 409 no-op
            # — an unconditional null patch here would wipe a peer that
            # legitimately took the lease over after our last renew.
            try:
                lease = await self.client.get(
                    GROUP, VERSION, self.k8s_namespace, PLURAL, self.name
                )
                spec = lease.get("spec") or {}
                if spec.get("holderIdentity") == self.identity:
                    body: dict = {
                        "spec": {"holderIdentity": None, "renewTime": None}
                    }
                    rv = (lease.get("metadata") or {}).get("resourceVersion")
                    if rv is not None:
                        body["metadata"] = {"resourceVersion": str(rv)}
                    await self.client.patch(
                        GROUP, VERSION, self.k8s_namespace, PLURAL,
                        self.name, body,
                    )
            except KubeApiError as exc:
                if exc.status != 409:  # lost a race: someone else owns it
                    logger.warning(
                        "leader election %s: graceful release failed (%s)",
                        self.name, exc,
                    )
            except Exception as exc:
                # Release is best-effort (the lease expires on its own),
                # but the failure must not be invisible.
                logger.debug(
                    "leader election %s: graceful release errored (%s)",
                    self.name, exc,
                )
            self._become(False)
