"""Kubernetes operator: reconcile GraphDeployment CRs to running services.

Reference parity:
  - deploy/operator/internal/controller/dynamographdeployment_controller.go:110
    — Reconcile(): drive observed state to CR spec, write status back.
  - deploy/operator/api/v1alpha1/dynamographdeploymentrequest_types.go — the
    DGDR flow: an SLA-profiling request CR that produces a sized
    DynamoGraphDeployment.

This operator watches the cluster through the minimal REST client
(deploy/k8s_client.py) and maps each DynamoTpuGraphDeployment CR onto a
GraphController (deploy/controller.py) — the CR's spec IS the
GraphDeployment document, so specs move unchanged between `kubectl apply`
and the local `python -m dynamo_tpu.deploy apply`. Worker pods vs local
processes is a connector concern: the default ProcessConnector supervises
subprocesses (one per replica) on the operator's node, which is also
exactly what the envtest-style fake-apiserver tests observe.

Level-triggered loop per kind: list → reconcile all → watch until the
window closes → repeat. Planner-driven replica changes arrive as CR spec
updates (the planner patches the CR, same as the reference's
kubernetes_connector) or via the in-process discovery override the
GraphController already honors.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, Optional

from dynamo_tpu.deploy.controller import GraphController
from dynamo_tpu.deploy.k8s_client import KubeApiError, KubeClient
from dynamo_tpu.deploy.spec import GraphDeployment
from dynamo_tpu.runtime.tasks import reap_task
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

GROUP = "dynamo-tpu.io"
VERSION = "v1alpha1"
GD_PLURAL = "graphdeployments"
DGDR_PLURAL = "graphdeploymentrequests"
SA_PLURAL = "scalingadapters"
CKPT_PLURAL = "checkpoints"


def _identity_hash(identity: Dict[str, Any]) -> str:
    """Deterministic hash of a checkpoint identity (dedup key; the role of
    the reference's IdentityHash on DynamoCheckpoint status)."""
    import hashlib
    import json

    return hashlib.sha256(
        json.dumps(identity, sort_keys=True).encode()
    ).hexdigest()[:16]


def deployment_from_cr(cr: Dict[str, Any]) -> GraphDeployment:
    """CR object → GraphDeployment. metadata.name IS the deployment name
    (kube convention): a spec-level "name" is ignored so pod labels, the
    orphan sweep, and status all key on one identity."""
    spec = dict(cr.get("spec") or {})
    spec["name"] = cr["metadata"]["name"]
    return GraphDeployment.from_dict(spec)


class K8sGraphOperator:
    """One operator process: watches both CRD kinds in one k8s namespace."""

    def __init__(
        self,
        client: KubeClient,
        *,
        k8s_namespace: str = "default",
        discovery: Optional[Any] = None,
        reconcile_interval_s: float = 1.0,
        watch_timeout_s: float = 10.0,
        sla_profiles: Optional[Any] = None,  # List[ConfigProfile] for DGDR
        pod_backend: bool = False,  # actuate CRs as cluster pods, not procs
        checkpoint_runner: Optional[Any] = None,  # async (identity) → location
        leader_elector: Optional[Any] = None,  # deploy.leader.LeaderElector
    ) -> None:
        self.client = client
        self.k8s_namespace = k8s_namespace
        self.discovery = discovery
        self.reconcile_interval_s = reconcile_interval_s
        self.watch_timeout_s = watch_timeout_s
        self.sla_profiles = sla_profiles
        self.pod_backend = pod_backend
        self.checkpoint_runner = checkpoint_runner
        self.leader_elector = leader_elector
        self._swept_orphans = False
        self._controllers: Dict[str, GraphController] = {}
        self._specs: Dict[str, str] = {}  # name → serialized spec (drift check)
        self._dgdr_done: Dict[str, str] = {}  # name → outcome
        self._ckpt_tasks: Dict[str, asyncio.Task] = {}  # name → running job
        self._tasks: list = []
        self._stop = asyncio.Event()
        self.reconciles = 0
        self.adapter_scales = 0  # adapter-driven replica patches applied

    # -- GraphDeployment reconcile ----------------------------------------

    async def _apply_cr(self, cr: Dict[str, Any]) -> None:
        name = cr["metadata"]["name"]
        import json

        def _shape_key(spec: Dict[str, Any]) -> str:
            # Spec minus per-service replica counts: a replicas-only change
            # scales in place; anything else (args, env, restart id, service
            # set) rebuilds the controller — a rolling restart, like the
            # reference operator's pod-template change handling.
            shaped = json.loads(json.dumps(spec))
            for svc in (shaped.get("services") or {}).values():
                svc.pop("replicas", None)
            return json.dumps(shaped, sort_keys=True)

        spec = cr.get("spec") or {}
        spec_key = _shape_key(spec)
        ctrl = self._controllers.get(name)
        if ctrl is not None and self._specs.get(name) != spec_key:
            logger.info("GraphDeployment %s shape changed: rolling restart", name)
            await ctrl.stop(teardown=True)
            ctrl = None
            self._controllers.pop(name, None)
        if ctrl is not None:
            # Replicas-only updates flow through the live controller.
            ctrl.deployment = deployment_from_cr(cr)
        if ctrl is None:
            dep = deployment_from_cr(cr)
            connector = None
            if self.pod_backend:
                from dynamo_tpu.deploy.pod_connector import PodConnector

                connector = PodConnector(
                    self.client, dep, k8s_namespace=self.k8s_namespace
                )
            ctrl = GraphController(
                dep, discovery=self.discovery,
                reconcile_interval_s=self.reconcile_interval_s,
                connector=connector,
            )
            self._controllers[name] = ctrl
        self._specs[name] = spec_key
        counts = await ctrl.reconcile_once()
        self.reconciles += 1
        status = ctrl.status()
        status["observedCounts"] = counts
        try:
            await self.client.patch_status(
                GROUP, VERSION, self.k8s_namespace, GD_PLURAL, name,
                {"services": status["services"], "reconciles": status["reconciles"]},
            )
        except KubeApiError as exc:
            logger.warning("status patch for %s failed: %s", name, exc)

    async def _remove_cr(self, name: str) -> None:
        ctrl = self._controllers.pop(name, None)
        self._specs.pop(name, None)
        if ctrl is not None:
            logger.info("GraphDeployment %s deleted: tearing down", name)
            await ctrl.stop(teardown=True)

    async def reconcile_deployments_once(self) -> None:
        items, _rv = await self.client.list(
            GROUP, VERSION, self.k8s_namespace, GD_PLURAL
        )
        seen = set()
        for cr in items:
            seen.add(cr["metadata"]["name"])
            try:
                await self._apply_cr(cr)
            except Exception:
                logger.exception(
                    "reconcile of %s failed", cr["metadata"]["name"]
                )
        for name in list(self._controllers):
            if name not in seen:
                await self._remove_cr(name)
        if self.pod_backend and not self._swept_orphans:
            # Only the operator-was-down window can create orphans (live CR
            # deletion tears down via _remove_cr), so one sweep at startup
            # suffices — no per-pass namespace LIST tax.
            await self._sweep_orphan_pods(seen)
            self._swept_orphans = True

    async def _sweep_orphan_pods(self, live_crs) -> None:
        """Delete labeled pods/services whose deployment CR is gone — the
        role ownerReference GC plays for the reference operator's child
        workloads. Matters after operator restart: pods survive the
        restart (PodConnector.survives_restart), so a CR deleted while no
        operator was watching leaves orphans only this sweep can see."""
        from dynamo_tpu.deploy.pod_connector import LABEL_DEPLOYMENT

        # Existence selector: only objects this operator family labeled
        # (server-side filtering on a real apiserver).
        try:
            pods = await self.client.list_core(
                self.k8s_namespace, "pods", label_selector=LABEL_DEPLOYMENT
            )
            services = await self.client.list_core(
                self.k8s_namespace, "services",
                label_selector=LABEL_DEPLOYMENT,
            )
        except KubeApiError:
            return
        swept = set()
        for plural, objs in (("pods", pods), ("services", services)):
            for obj in objs:
                owner = (obj.get("metadata", {}).get("labels") or {}).get(
                    LABEL_DEPLOYMENT
                )
                if owner and owner not in live_crs:
                    swept.add(owner)
                    try:
                        await self.client.delete_core(
                            self.k8s_namespace, plural,
                            obj["metadata"]["name"],
                        )
                    except KubeApiError:
                        pass
        for owner in swept:
            logger.info("swept orphaned objects of deleted CR %s", owner)

    # -- DGDR: SLA-profiling request → sized deployment --------------------

    async def reconcile_requests_once(self) -> None:
        try:
            items, _rv = await self.client.list(
                GROUP, VERSION, self.k8s_namespace, DGDR_PLURAL
            )
        except KubeApiError as exc:
            if exc.status == 404:  # CRD not installed: DGDR flow disabled
                return
            raise
        for cr in items:
            name = cr["metadata"]["name"]
            if self._dgdr_done.get(name) or (cr.get("status") or {}).get("state") in (
                "deployed", "failed"
            ):
                continue
            try:
                await self._fulfill_request(cr)
                self._dgdr_done[name] = "deployed"
            except Exception as exc:
                logger.exception("DGDR %s failed", name)
                self._dgdr_done[name] = "failed"
                try:
                    await self.client.patch_status(
                        GROUP, VERSION, self.k8s_namespace, DGDR_PLURAL, name,
                        {"state": "failed", "message": str(exc)[:500]},
                    )
                except KubeApiError:
                    pass

    async def _fulfill_request(self, cr: Dict[str, Any]) -> None:
        """Run SLA sizing (profiler/sla.py) and create the sized
        GraphDeployment (ref: dynamographdeploymentrequest_types.go flow)."""
        from dynamo_tpu.profiler.sla import SlaTargets, Workload, recommend

        name = cr["metadata"]["name"]
        spec = cr.get("spec") or {}
        targets = SlaTargets(
            ttft_s=float(spec.get("sla", {}).get("ttft_s", 0.5)),
            itl_s=float(spec.get("sla", {}).get("itl_s", 0.02)),
        )
        wl = spec.get("workload", {})
        workload = Workload(
            request_rate=float(wl.get("requests_per_s", 1.0)),
            isl=float(wl.get("isl", 512)),
            osl=float(wl.get("osl", 128)),
        )
        profiles = self.sla_profiles
        if profiles is None:
            raise RuntimeError(
                "operator has no profile tables (sla_profiles); supply "
                "pre-swept ConfigProfiles or run the profiler first"
            )
        report = recommend(profiles, targets, workload)
        if report.chosen is None:
            raise RuntimeError(
                f"no config meets the SLA: {report.rejected}"
            )
        rec = report.chosen
        template = spec.get("template") or {}
        services = dict(template.get("services") or {})
        # Size the worker pools the recommendation asked for.
        for svc_name, svc in services.items():
            role = svc.get("planner_role", "decode")
            if svc.get("planner_scaled") or svc.get("sized"):
                svc = dict(svc)
                svc["replicas"] = (
                    rec.prefill_workers if role == "prefill" else rec.decode_workers
                )
                services[svc_name] = svc
        body = {
            "apiVersion": f"{GROUP}/{VERSION}",
            "kind": "DynamoTpuGraphDeployment",
            "metadata": {"name": spec.get("deploymentName", f"{name}-deployment")},
            "spec": {**template, "services": services},
        }
        try:
            await self.client.create(
                GROUP, VERSION, self.k8s_namespace, GD_PLURAL, body
            )
        except KubeApiError as exc:
            if exc.status != 409:  # already created by a prior pass
                raise
        await self.client.patch_status(
            GROUP, VERSION, self.k8s_namespace, DGDR_PLURAL, name,
            {
                "state": "deployed",
                "deployment": body["metadata"]["name"],
                "recommendation": {
                    "config": rec.config_name,
                    "prefill_workers": rec.prefill_workers,
                    "decode_workers": rec.decode_workers,
                    "total_chips": rec.total_chips,
                },
            },
        )
        logger.info(
            "DGDR %s → deployment %s (%s: %dP/%dD, %d chips)",
            name, body["metadata"]["name"], rec.config_name,
            rec.prefill_workers, rec.decode_workers, rec.total_chips,
        )

    # -- ScalingAdapter: the ONLY writer of GD service replicas ------------
    #
    # Autoscalers (planner, HPA-style controllers) patch the adapter CR's
    # spec.replicas; this reconciler copies it onto the target
    # GraphDeployment's service — the reference's anti-conflict design
    # (ref: deploy/operator/api/v1alpha1/
    # dynamographdeploymentscalingadapter_types.go:27-67: adapter is the
    # intermediary so multiple autoscalers never race on the DGD itself).

    async def reconcile_adapters_once(self) -> None:
        try:
            items, _rv = await self.client.list(
                GROUP, VERSION, self.k8s_namespace, SA_PLURAL
            )
        except KubeApiError as exc:
            if exc.status == 404:  # CRD not installed: adapters disabled
                return
            raise
        for cr in items:
            # Per-CR isolation (same as the GD pass): one malformed adapter
            # must not starve the rest of the operator's reconcile loop.
            try:
                await self._reconcile_adapter(cr)
            except Exception:
                logger.exception(
                    "adapter %s reconcile failed", cr["metadata"]["name"]
                )

    async def _reconcile_adapter(self, cr: Dict[str, Any]) -> None:
        import time as _time

        name = cr["metadata"]["name"]
        spec = cr.get("spec") or {}
        ref = spec.get("dgdRef") or {}
        gd_name = ref.get("name")
        svc_name = ref.get("serviceName")
        try:
            desired = int(spec.get("replicas"))
        except (TypeError, ValueError):
            await self._patch_adapter_status(
                name, {"message": "spec.replicas must be an integer"}
            )
            return
        if not gd_name or not svc_name:
            return
        try:
            gd = await self.client.get(
                GROUP, VERSION, self.k8s_namespace, GD_PLURAL, gd_name
            )
        except KubeApiError as exc:
            if exc.status == 404:
                await self._patch_adapter_status(
                    name, {"message": f"GraphDeployment {gd_name} not found"}
                )
                return
            raise
        services = (gd.get("spec") or {}).get("services") or {}
        svc = services.get(svc_name)
        if svc is None:
            await self._patch_adapter_status(
                name, {"message": f"service {svc_name!r} not in {gd_name}"}
            )
            return
        observed_spec = int(svc.get("replicas", 1))
        # status.replicas backs the HPA scale subresource: report the
        # OBSERVED ready count (GD status) only. When the GD has no ready
        # count yet, falling back to the GD spec would echo the replica
        # count a previous reconcile just WROTE — phantom capacity that
        # makes an autoscaler believe a scale-up already landed. Report
        # the adapter's last known ready count instead (0 before the
        # first readiness report).
        ready = (
            (gd.get("status") or {}).get("services") or {}
        ).get(svc_name, {}).get("ready")
        if ready is None:
            last_known = (cr.get("status") or {}).get("replicas")
            ready = int(last_known) if last_known is not None else 0
        status: Dict[str, Any] = {
            "replicas": int(ready),
            "selector": f"dynamo-tpu.io/deployment={gd_name}",
            "message": "",
        }
        if observed_spec != desired:
            await self.client.patch(
                GROUP, VERSION, self.k8s_namespace, GD_PLURAL, gd_name,
                {"spec": {"services": {svc_name: {"replicas": desired}}}},
            )
            self.adapter_scales += 1
            status["lastScaleTime"] = _time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", _time.gmtime()
            )
            logger.info(
                "adapter %s: %s/%s replicas %d → %d",
                name, gd_name, svc_name, observed_spec, desired,
            )
        await self._patch_adapter_status(name, status)

    async def _patch_adapter_status(self, name: str, status: Dict[str, Any]):
        try:
            await self.client.patch_status(
                GROUP, VERSION, self.k8s_namespace, SA_PLURAL, name, status
            )
        except KubeApiError:
            pass

    # -- Checkpoint: cluster-driveable warm-restart artifacts --------------
    #
    # A Checkpoint CR names a model identity; fulfilling it runs the warm
    # tier (weight cache + jax compile cache priming) so later workers of
    # that identity restart warm. Phases Pending → Creating → Ready/Failed
    # mirror the reference (ref: deploy/operator/api/v1alpha1/
    # dynamocheckpoint_types.go: identity→job→tar flow; here the "job" is
    # the in-tree checkpoint runner instead of a CRIU tar builder).

    async def reconcile_checkpoints_once(self) -> None:
        try:
            items, _rv = await self.client.list(
                GROUP, VERSION, self.k8s_namespace, CKPT_PLURAL
            )
        except KubeApiError as exc:
            if exc.status == 404:
                return
            raise
        for cr in items:
            name = cr["metadata"]["name"]
            phase = (cr.get("status") or {}).get("phase")
            if phase in ("Ready", "Failed") or name in self._ckpt_tasks:
                continue
            identity = (cr.get("spec") or {}).get("identity") or {}
            ih = _identity_hash(identity)
            await self._patch_ckpt_status(
                name, {"phase": "Creating", "identityHash": ih}
            )
            self._ckpt_tasks[name] = asyncio.get_running_loop().create_task(
                self._run_checkpoint(name, identity, ih),
                name=f"ckpt-{name}",
            )

    async def _run_checkpoint(self, name, identity, ih) -> None:
        runner = self.checkpoint_runner
        if runner is None:
            from dynamo_tpu.deploy.checkpoint_job import run_checkpoint_job

            runner = run_checkpoint_job
        try:
            location = await runner(identity)
            await self._patch_ckpt_status(
                name,
                {"phase": "Ready", "identityHash": ih, "location": location},
            )
            logger.info("checkpoint %s ready at %s", name, location)
        except Exception as exc:
            logger.exception("checkpoint %s failed", name)
            await self._patch_ckpt_status(
                name,
                {
                    "phase": "Failed",
                    "identityHash": ih,
                    "message": str(exc)[:500],
                },
            )
        finally:
            self._ckpt_tasks.pop(name, None)

    async def _patch_ckpt_status(self, name: str, status: Dict[str, Any]):
        try:
            await self.client.patch_status(
                GROUP, VERSION, self.k8s_namespace, CKPT_PLURAL, name, status
            )
        except KubeApiError:
            pass

    # -- lifecycle ---------------------------------------------------------

    async def run(self) -> None:
        """Level-triggered loop: reconcile everything, then watch until the
        window closes (events only wake us early — the list is the truth)."""
        if self.leader_elector is not None:
            self.leader_elector.start()
        while not self._stop.is_set():
            if self.leader_elector is not None and not self.leader_elector.is_leader:
                # Replicated operators: only the lease holder reconciles
                # (ref: deploy/operator/cmd/main.go --leader-elect). A
                # candidate parks until it acquires; its controllers stay
                # cold so two operators never double-actuate.
                await self.leader_elector.wait_leader(
                    timeout=self.reconcile_interval_s
                )
                continue
            # Adapters first: their replica patches land before the GD
            # pass reads the specs, so a scale round-trips in ONE pass.
            # Each sub-pass is isolated — an optional feature failing (e.g.
            # a 403 on the adapter list from a stale ClusterRole) must not
            # starve deployment reconciliation.
            for pass_fn in (
                self.reconcile_adapters_once,
                self.reconcile_deployments_once,
                self.reconcile_requests_once,
                self.reconcile_checkpoints_once,
            ):
                try:
                    await pass_fn()
                except Exception:
                    logger.exception(
                        "operator pass %s failed", pass_fn.__name__
                    )
            # Block on watch streams (ALL reconciled kinds — a planner
            # write to a ScalingAdapter or a new Checkpoint must wake the
            # loop as promptly as a GD change) until something changes or
            # the window times out, then loop back to a full re-list.
            async def _first_event(plural: str) -> None:
                try:
                    async for _event in self.client.watch(
                        GROUP, VERSION, self.k8s_namespace, plural,
                        timeout_s=self.watch_timeout_s,
                    ):
                        return
                except Exception:
                    # Uninstalled CRD (404) or transient apiserver error:
                    # park for the window so this watcher neither wakes the
                    # loop early nor busy-spins it.
                    await asyncio.sleep(self.watch_timeout_s)

            tasks = [
                asyncio.ensure_future(_first_event(p))
                for p in (GD_PLURAL, SA_PLURAL, CKPT_PLURAL)
            ]
            _done, pending = await asyncio.wait(
                tasks, return_when=asyncio.FIRST_COMPLETED
            )
            for t in pending:
                t.cancel()
            for t in pending:
                await reap_task(t, "k8s-operator watch", logger)

    def start(self) -> None:
        self._stop.clear()
        self._tasks = [
            asyncio.get_running_loop().create_task(self.run(), name="k8s-operator")
        ]

    async def stop(self, *, teardown: bool = True) -> None:
        self._stop.set()
        for t in list(self._ckpt_tasks.values()):
            t.cancel()
            await reap_task(t, "checkpoint job", logger)
        self._ckpt_tasks = {}
        for t in self._tasks:
            t.cancel()
            await reap_task(t, "k8s-operator run loop", logger)
        self._tasks = []
        if self.leader_elector is not None:
            # Release the lease only AFTER the run loop has fully exited:
            # releasing mid-pass would let a standby start actuating while
            # this instance's in-flight reconcile is still mutating pods.
            await self.leader_elector.stop()
        for name in list(self._controllers):
            ctrl = self._controllers.pop(name)
            # Operator exit is NOT CR deletion: actuators whose workloads
            # outlive the operator (pods) are left running for the next
            # operator instance to re-adopt; only local subprocesses die
            # with their supervisor.
            survives = getattr(ctrl._connector, "survives_restart", False)
            await ctrl.stop(teardown=teardown and not survives)
        await self.client.close()
