"""Validating admission webhook for the CRDs.

Reference parity: the reference operator registers validating webhooks for
its CRD kinds (deploy/operator/ webhook setup via controller-runtime) so a
malformed DynamoGraphDeployment is rejected at `kubectl apply` time rather
than crash-looping the reconciler. Same role here: an aiohttp server
speaking the admission/v1 AdmissionReview contract; validation IS the spec
parser (deploy/spec.py GraphDeployment.from_dict + validate) plus
pod-target sanity checks, so apply-time rules can never drift from what
the operator actually accepts.

Serving: in-cluster this sits behind a Service with TLS certs mounted
(--tls-cert/--tls-key; kube requires HTTPS for webhooks); tests drive the
handler over plain HTTP.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional, Tuple

from aiohttp import web

from dynamo_tpu.deploy.spec import GraphDeployment
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def validate_graph_deployment(obj: Dict[str, Any]) -> Tuple[bool, str]:
    """(allowed, message). The single source of validation truth: parse
    with the SAME code the operator reconciles with."""
    try:
        spec = dict(obj.get("spec") or {})
        spec["name"] = (obj.get("metadata") or {}).get("name", "unnamed")
        dep = GraphDeployment.from_dict(spec)
    except Exception as exc:
        return False, f"invalid spec: {exc}"
    for name, svc in dep.services.items():
        # (multihost groups without an explicit port get the render-time
        # default coordinator port — allowed, not validated here)
        if svc.hosts_per_replica < 1:
            return False, f"service {name}: hosts_per_replica must be >= 1"
        if svc.chips_per_host < 0:
            return False, f"service {name}: negative chips_per_host"
        if (svc.tpu_topology and not svc.tpu_accelerator) or (
            svc.tpu_accelerator and not svc.tpu_topology
        ):
            return False, (
                f"service {name}: tpu_accelerator and tpu_topology must be "
                "set together (GKE schedules podslices on the pair)"
            )
    return True, "ok"


def validate_request(request_obj: Dict[str, Any]) -> Tuple[bool, str]:
    """DGDR validation: SLA + workload fields must be positive numbers."""
    spec = request_obj.get("spec") or {}
    sla = spec.get("sla") or {}
    wl = spec.get("workload") or {}
    for key, doc in (("ttft_s", sla), ("itl_s", sla)):
        if key in doc:
            try:
                if float(doc[key]) <= 0:
                    return False, f"sla.{key} must be > 0"
            except (TypeError, ValueError):
                return False, f"sla.{key} is not a number"
    for key in ("isl", "osl", "requests_per_s"):
        if key in wl:
            try:
                if float(wl[key]) <= 0:
                    return False, f"workload.{key} must be > 0"
            except (TypeError, ValueError):
                return False, f"workload.{key} is not a number"
    if not (spec.get("template") or {}).get("services"):
        return False, "template.services is required"
    return True, "ok"


def validate_scaling_adapter(obj: Dict[str, Any]) -> Tuple[bool, str]:
    """Adapter validation: non-negative replicas + complete dgdRef."""
    spec = obj.get("spec") or {}
    replicas = spec.get("replicas")
    try:
        if int(replicas) < 0:
            return False, "spec.replicas must be >= 0"
    except (TypeError, ValueError):
        return False, "spec.replicas must be an integer"
    ref = spec.get("dgdRef") or {}
    if not ref.get("name") or not ref.get("serviceName"):
        return False, "spec.dgdRef.name and spec.dgdRef.serviceName required"
    return True, "ok"


def validate_checkpoint(obj: Dict[str, Any]) -> Tuple[bool, str]:
    """Checkpoint validation: a model identity is required."""
    spec = obj.get("spec") or {}
    identity = spec.get("identity") or {}
    if not identity.get("model"):
        return False, "spec.identity.model is required"
    quant = identity.get("quantization")
    if quant not in (None, "", "int8"):
        return False, f"unsupported quantization {quant!r} (int8 only)"
    return True, "ok"


_KIND_VALIDATORS = {
    "DynamoTpuGraphDeployment": validate_graph_deployment,
    "DynamoTpuGraphDeploymentRequest": validate_request,
    "DynamoTpuScalingAdapter": validate_scaling_adapter,
    "DynamoTpuCheckpoint": validate_checkpoint,
}


def review_response(review: Dict[str, Any]) -> Dict[str, Any]:
    """AdmissionReview in → AdmissionReview out (admission.k8s.io/v1)."""
    req = review.get("request") or {}
    uid = req.get("uid", "")
    obj = req.get("object")
    if obj is None:
        # DELETE (and any op where object is null) carries no new spec to
        # validate — allow rather than deny on an empty dict, so the handler
        # stays safe if DELETE is ever added to the webhook rules.
        return _admission_response(
            uid, True, f"no object for {req.get('operation', '?')}"
        )
    kind = (obj.get("kind") or req.get("kind", {}).get("kind") or "")
    validator = _KIND_VALIDATORS.get(kind)
    if validator is None:
        allowed, message = True, f"kind {kind!r} not validated"
    else:
        allowed, message = validator(obj)
        if not allowed:
            logger.info("denied %s %s: %s", kind,
                        (obj.get("metadata") or {}).get("name"), message)
    return _admission_response(uid, allowed, message)


def _admission_response(uid: str, allowed: bool, message: str) -> Dict[str, Any]:
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "response": {
            "uid": uid,
            "allowed": allowed,
            **(
                {}
                if allowed
                else {"status": {"code": 422, "message": message}}
            ),
        },
    }


def build_app() -> web.Application:
    async def handle(request: web.Request) -> web.Response:
        try:
            review = await request.json()
        except json.JSONDecodeError:
            return web.json_response({"error": "bad json"}, status=400)
        return web.json_response(review_response(review))

    async def healthz(_request: web.Request) -> web.Response:
        return web.json_response({"ok": True})

    app = web.Application()
    app.router.add_post("/validate", handle)
    app.router.add_get("/healthz", healthz)
    return app


async def serve(
    port: int = 9443,
    tls_cert: Optional[str] = None,
    tls_key: Optional[str] = None,
) -> web.AppRunner:
    import ssl

    if bool(tls_cert) != bool(tls_key):
        # Silently serving plain HTTP here would fail every admission
        # request's mandatory TLS handshake with no hint in our log.
        raise ValueError("--tls-cert and --tls-key must be set together")
    ctx = None
    if tls_cert and tls_key:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(tls_cert, tls_key)
    runner = web.AppRunner(build_app())
    await runner.setup()
    site = web.TCPSite(runner, "0.0.0.0", port, ssl_context=ctx)
    await site.start()
    logger.info("admission webhook on :%d (%s)", port,
                "https" if ctx else "http")
    return runner
