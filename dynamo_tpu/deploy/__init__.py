"""Deployment control plane: graph specs + a reconciling controller.

Reference parity: deploy/operator (the Go Kubernetes operator reconciling
DynamoGraphDeployment CRDs into pods) re-designed for this framework's
deployment unit — OS processes on TPU hosts. The same spec shape
(services → replicas/command/env, restart policy) drives:

  - ProcessBackend: subprocess supervision on one host (functional here),
  - the k8s manifests under deploy/k8s/ for cluster deployments (the CRD
    and an example CR, applied by any kubectl — the operator pattern
    documented for clusters this environment can't reach).

The controller also closes the operator↔planner loop: the planner's
VirtualConnector publishes desired worker counts to the discovery plane,
and the controller folds them into its reconcile pass — exactly the
reference flow (planner patches the CRD, operator reconciles pods).
"""

from dynamo_tpu.deploy.spec import GraphDeployment, ServiceSpec
from dynamo_tpu.deploy.controller import GraphController

__all__ = ["GraphDeployment", "ServiceSpec", "GraphController"]
