"""GraphDeployment spec: the CRD shape as a Python/YAML document.

Reference parity: deploy/operator/api/v1alpha1/dynamographdeployment_types.go
(DynamoGraphDeploymentSpec — services map with shared component spec,
global envs, restart policy). Service kinds map to this framework's
builtin service modules; explicit commands cover anything else.

YAML example:

    name: my-deployment
    namespace: prod
    envs:
      DYN_TPU_DISCOVERY: discd
    services:
      discd:
        kind: discd
        replicas: 1
      backend:
        kind: worker
        replicas: 2
        args: ["--model", "tiny", "--max-num-seqs", "16"]
        planner_scaled: true      # planner desired counts override replicas
      frontend:
        kind: frontend
        replicas: 1
        args: ["--http-port", "8080"]
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

# service kind → module (the builtin components a graph can deploy)
KIND_MODULES = {
    "frontend": "dynamo_tpu.frontend",
    "worker": "dynamo_tpu.worker",
    "mocker": "dynamo_tpu.mocker",
    "discd": "dynamo_tpu.discd",
    "planner": "dynamo_tpu.planner",
    "grpc": "dynamo_tpu.grpc",
    "global_router": "dynamo_tpu.global_router",
    "kvstore": "dynamo_tpu.kvbm",
    "encoder": "dynamo_tpu.multimodal",
}


@dataclass
class ServiceSpec:
    """(ref: DynamoComponentDeploymentSharedSpec — replicas/envs/args)"""

    kind: str = ""  # one of KIND_MODULES, or "" with an explicit command
    replicas: int = 1
    args: List[str] = field(default_factory=list)
    command: Optional[List[str]] = None  # overrides kind
    env: Dict[str, str] = field(default_factory=dict)
    # planner-managed pool: desired counts from the planner override replicas
    # (ref: the planner patching CRD replicas for the operator to reconcile)
    planner_scaled: bool = False
    planner_role: str = "decode"  # which count of the plan applies
    grace_period_s: float = 10.0

    def resolved_command(self) -> List[str]:
        if self.command:
            return list(self.command)
        module = KIND_MODULES.get(self.kind)
        if module is None:
            raise ValueError(
                f"service kind {self.kind!r} unknown "
                f"(builtin: {sorted(KIND_MODULES)}) and no command given"
            )
        return [sys.executable, "-m", module, *self.args]


@dataclass
class GraphDeployment:
    """(ref: DynamoGraphDeploymentSpec)"""

    name: str
    namespace: str = "dynamo"
    services: Dict[str, ServiceSpec] = field(default_factory=dict)
    envs: Dict[str, str] = field(default_factory=dict)
    # restart.id change triggers a rolling restart (ref: Restart.ID)
    restart_id: str = ""

    @classmethod
    def from_dict(cls, doc: Dict[str, Any]) -> "GraphDeployment":
        services = {}
        for name, s in (doc.get("services") or {}).items():
            services[name] = ServiceSpec(
                kind=s.get("kind", ""),
                replicas=int(s.get("replicas", 1)),
                args=[str(a) for a in s.get("args", [])],
                command=s.get("command"),
                env={k: str(v) for k, v in (s.get("env") or {}).items()},
                planner_scaled=bool(s.get("planner_scaled", False)),
                planner_role=s.get("planner_role", "decode"),
                grace_period_s=float(s.get("grace_period_s", 10.0)),
            )
        dep = cls(
            name=doc.get("name", "deployment"),
            namespace=doc.get("namespace", "dynamo"),
            services=services,
            envs={k: str(v) for k, v in (doc.get("envs") or {}).items()},
            restart_id=str(doc.get("restart", {}).get("id", "")) if doc.get("restart") else "",
        )
        dep.validate()
        return dep

    @classmethod
    def from_file(cls, path: str) -> "GraphDeployment":
        import yaml

        with open(path) as f:
            return cls.from_dict(yaml.safe_load(f))

    def validate(self) -> None:
        if not self.services:
            raise ValueError("deployment has no services")
        for name, svc in self.services.items():
            svc.resolved_command()  # raises on unknown kind
            if svc.replicas < 0:
                raise ValueError(f"service {name}: negative replicas")
