"""GraphDeployment spec: the CRD shape as a Python/YAML document.

Reference parity: deploy/operator/api/v1alpha1/dynamographdeployment_types.go
(DynamoGraphDeploymentSpec — services map with shared component spec,
global envs, restart policy). Service kinds map to this framework's
builtin service modules; explicit commands cover anything else.

YAML example:

    name: my-deployment
    namespace: prod
    envs:
      DYN_TPU_DISCOVERY: discd
    services:
      discd:
        kind: discd
        replicas: 1
      backend:
        kind: worker
        replicas: 2
        args: ["--model", "tiny", "--max-num-seqs", "16"]
        planner_scaled: true      # planner desired counts override replicas
      frontend:
        kind: frontend
        replicas: 1
        args: ["--http-port", "8080"]
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

# service kind → module (the builtin components a graph can deploy)
KIND_MODULES = {
    "frontend": "dynamo_tpu.frontend",
    "worker": "dynamo_tpu.worker",
    "mocker": "dynamo_tpu.mocker",
    "discd": "dynamo_tpu.discd",
    "planner": "dynamo_tpu.planner",
    "grpc": "dynamo_tpu.grpc",
    "global_router": "dynamo_tpu.global_router",
    "kvstore": "dynamo_tpu.kvbm",
    "encoder": "dynamo_tpu.multimodal",
}


@dataclass
class ServiceSpec:
    """(ref: DynamoComponentDeploymentSharedSpec — replicas/envs/args)"""

    kind: str = ""  # one of KIND_MODULES, or "" with an explicit command
    replicas: int = 1
    args: List[str] = field(default_factory=list)
    command: Optional[List[str]] = None  # overrides kind
    env: Dict[str, str] = field(default_factory=dict)
    # planner-managed pool: desired counts from the planner override replicas
    # (ref: the planner patching CRD replicas for the operator to reconcile)
    planner_scaled: bool = False
    planner_role: str = "decode"  # which count of the plan applies
    grace_period_s: float = 10.0
    # -- pod-target fields (used by the PodConnector actuator; ignored by
    # the local ProcessConnector). One REPLICA of a multihost worker group
    # is hosts_per_replica pods wired together via the DYN_TPU_* contract
    # (parallel/multihost.py), the TPU analog of the reference's
    # multinode Grove/LWS grouping (ref: deploy/operator/api/v1alpha1/
    # dynamocomponentdeployment_types.go multinode fields).
    image: str = ""  # container image; "" inherits the deployment default
    hosts_per_replica: int = 1
    chips_per_host: int = 0  # google.com/tpu resource limit (0 = none)
    tpu_accelerator: str = ""  # gke nodeSelector accelerator value
    tpu_topology: str = ""  # gke nodeSelector topology value
    node_selector: Dict[str, str] = field(default_factory=dict)
    port: int = 0  # containerPort + coordinator port for multihost groups
    # System-server port of the service's worker process (--system-port).
    # > 0 wires the rolling-restart contract into the pod: a preStop
    # httpGet hook hits GET /drain?start=1 (the kubelet blocks on the
    # response, which is the live-handoff drain completing) and the pod's
    # terminationGracePeriodSeconds is sized to drain_deadline_s + margin.
    # It also renders the crash-plane probe split: livenessProbe /healthz
    # (process-up only; a restore in progress is NOT a reason to restart)
    # and readinessProbe /readyz (warm restore + registration done —
    # traffic only past this gate).
    system_port: int = 0
    # Drain budget advertised to k8s (DYN_TPU_DRAIN_DEADLINE_S should
    # match); only meaningful with system_port > 0.
    drain_deadline_s: float = 30.0

    def resolved_command(self) -> List[str]:
        if self.command:
            return list(self.command)
        module = KIND_MODULES.get(self.kind)
        if module is None:
            raise ValueError(
                f"service kind {self.kind!r} unknown "
                f"(builtin: {sorted(KIND_MODULES)}) and no command given"
            )
        return [sys.executable, "-m", module, *self.args]

    def container_command(self) -> List[str]:
        """Command for a POD of this service: same resolution but with a
        bare ``python`` — the operator host's sys.executable path means
        nothing inside the container image."""
        if self.command:
            return list(self.command)
        return ["python", *self.resolved_command()[1:]]


@dataclass
class GraphDeployment:
    """(ref: DynamoGraphDeploymentSpec)"""

    name: str
    namespace: str = "dynamo"
    services: Dict[str, ServiceSpec] = field(default_factory=dict)
    envs: Dict[str, str] = field(default_factory=dict)
    # restart.id change triggers a rolling restart (ref: Restart.ID)
    restart_id: str = ""
    # default container image for pod-target services (ref: the operator's
    # component image resolution)
    image: str = "dynamo-tpu:latest"

    @classmethod
    def from_dict(cls, doc: Dict[str, Any]) -> "GraphDeployment":
        services = {}
        for name, s in (doc.get("services") or {}).items():
            services[name] = ServiceSpec(
                kind=s.get("kind", ""),
                replicas=int(s.get("replicas", 1)),
                args=[str(a) for a in s.get("args", [])],
                command=s.get("command"),
                env={k: str(v) for k, v in (s.get("env") or {}).items()},
                planner_scaled=bool(s.get("planner_scaled", False)),
                planner_role=s.get("planner_role", "decode"),
                grace_period_s=float(s.get("grace_period_s", 10.0)),
                image=s.get("image", ""),
                hosts_per_replica=int(s.get("hosts_per_replica", 1)),
                chips_per_host=int(s.get("chips_per_host", 0)),
                tpu_accelerator=s.get("tpu_accelerator", ""),
                tpu_topology=s.get("tpu_topology", ""),
                node_selector={
                    k: str(v) for k, v in (s.get("node_selector") or {}).items()
                },
                port=int(s.get("port", 0)),
                system_port=int(s.get("system_port", 0)),
                drain_deadline_s=float(s.get("drain_deadline_s", 30.0)),
            )
        dep = cls(
            name=doc.get("name", "deployment"),
            namespace=doc.get("namespace", "dynamo"),
            services=services,
            envs={k: str(v) for k, v in (doc.get("envs") or {}).items()},
            restart_id=str(doc.get("restart", {}).get("id", "")) if doc.get("restart") else "",
            image=doc.get("image", "dynamo-tpu:latest"),
        )
        dep.validate()
        return dep

    @classmethod
    def from_file(cls, path: str) -> "GraphDeployment":
        import yaml

        with open(path) as f:
            return cls.from_dict(yaml.safe_load(f))

    def validate(self) -> None:
        if not self.services:
            raise ValueError("deployment has no services")
        for name, svc in self.services.items():
            svc.resolved_command()  # raises on unknown kind
            if svc.replicas < 0:
                raise ValueError(f"service {name}: negative replicas")
