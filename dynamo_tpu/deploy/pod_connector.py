"""PodConnector: the operator's cluster actuator — CR services → pods.

Reference parity: deploy/operator/internal/controller/
dynamographdeployment_controller.go:110 turns a DynamoGraphDeployment CR
into cluster workloads (Deployments / multinode pod groups via Grove/LWS);
deploy/operator/api/v1alpha1/dynamocomponentdeployment_types.go carries the
multinode fields. This is the TPU-shaped equivalent: each service replica
becomes ``hosts_per_replica`` pods wired together through the
``DYN_TPU_COORDINATOR / DYN_TPU_NUM_PROCESSES / DYN_TPU_PROCESS_ID``
environment contract (parallel/multihost.py) — one pod per host of a
multihost SPMD worker group, scheduled onto a TPU podslice by GKE's
accelerator/topology node selectors.

Same duck-typed surface as planner/process_connector.ProcessConnector
(``apply_counts`` / ``counts`` / ``close``), so GraphController drives
local subprocesses and cluster pods through one code path; which actuator
a deployment gets is the operator's choice, not the spec's.

Level-triggered: every apply lists this deployment's pods by label and
diffs against the rendered desired set — missing pods are created,
unexpected / failed / template-drifted pods are deleted (recreated on the
next pass, the standard "delete and let reconcile heal" controller move).
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, List, Optional

from dynamo_tpu.deploy.k8s_client import KubeApiError, KubeClient
from dynamo_tpu.deploy.spec import GraphDeployment, ServiceSpec
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

LABEL_DEPLOYMENT = "dynamo-tpu.io/deployment"
LABEL_SERVICE = "dynamo-tpu.io/service"
LABEL_HASH = "dynamo-tpu.io/template-hash"
DEFAULT_COORD_PORT = 8476

# GKE TPU scheduling keys (public, documented node labels).
GKE_ACCELERATOR = "cloud.google.com/gke-tpu-accelerator"
GKE_TOPOLOGY = "cloud.google.com/gke-tpu-topology"
TPU_RESOURCE = "google.com/tpu"


def _template_hash(doc: Dict[str, Any]) -> str:
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True).encode()
    ).hexdigest()[:10]


def render_pod(
    dep: GraphDeployment,
    svc_name: str,
    svc: ServiceSpec,
    replica: int,
    host: int,
) -> Dict[str, Any]:
    """One pod of one host of one replica of a service.

    Multihost groups (hosts_per_replica > 1) get the ``DYN_TPU_*``
    jax.distributed contract: host 0 of the replica is the coordinator,
    addressed by stable pod DNS (hostname + the deployment's headless
    subdomain service)."""
    pod_name = f"{dep.name}-{svc_name}-{replica}-{host}"
    port = svc.port or DEFAULT_COORD_PORT
    env = {**dep.envs, **svc.env}
    H = max(svc.hosts_per_replica, 1)
    if H > 1:
        coord = f"{dep.name}-{svc_name}-{replica}-0.{dep.name}:{port}"
        env.update(
            DYN_TPU_COORDINATOR=coord,
            DYN_TPU_NUM_PROCESSES=str(H),
            DYN_TPU_PROCESS_ID=str(host),
        )
    node_selector = dict(svc.node_selector)
    if svc.tpu_accelerator:
        node_selector[GKE_ACCELERATOR] = svc.tpu_accelerator
    if svc.tpu_topology:
        node_selector[GKE_TOPOLOGY] = svc.tpu_topology
    container: Dict[str, Any] = {
        "name": svc_name,
        "image": svc.image or dep.image,
        "command": svc.container_command(),
        "env": [{"name": k, "value": v} for k, v in sorted(env.items())],
        "ports": [{"containerPort": port}],
    }
    if svc.chips_per_host > 0:
        container["resources"] = {
            "limits": {TPU_RESOURCE: str(svc.chips_per_host)}
        }
    if svc.system_port > 0:
        # Rolling-restart contract (runtime/drain.py): on pod deletion the
        # kubelet runs preStop and BLOCKS on its response — GET
        # /drain?start=1 returns only when the live-handoff drain finished
        # (every in-flight decode handed to a peer or migrated), so users
        # never observe the restart. SIGTERM afterwards is a no-op drain
        # re-trigger (idempotent). httpGet because the preStop action
        # cannot POST; the worker treats start=1 as the trigger.
        container["ports"].append({"containerPort": svc.system_port})
        container["lifecycle"] = {
            "preStop": {
                "httpGet": {
                    "path": "/drain?start=1",
                    "port": svc.system_port,
                }
            }
        }
        # Probe split (crash plane, runtime/system_server.py): /healthz is
        # liveness ONLY — the event loop turns; restarting would not help
        # a slow KV-checkpoint restore and would instead crash-loop it.
        # /readyz gates traffic: 503 until the worker restored its warm
        # cache and registered (and again while draining), so the kubelet
        # keeps an un-warm or departing worker out of Service endpoints
        # without ever killing it.
        container["livenessProbe"] = {
            "httpGet": {"path": "/healthz", "port": svc.system_port},
            "periodSeconds": 5,
            "failureThreshold": 3,
        }
        container["readinessProbe"] = {
            "httpGet": {"path": "/readyz", "port": svc.system_port},
            "periodSeconds": 2,
            "failureThreshold": 1,
        }
    spec: Dict[str, Any] = {
        "restartPolicy": "Never",  # the reconcile loop owns recreation
        "containers": [container],
        # Stable DNS through the deployment's headless service: pods of a
        # multihost group resolve each other before they are "ready".
        "hostname": pod_name,
        "subdomain": dep.name,
    }
    if svc.system_port > 0:
        # Budget = preStop drain + SIGTERM finally-path shutdown margin.
        spec["terminationGracePeriodSeconds"] = int(svc.drain_deadline_s) + 15
    if node_selector:
        spec["nodeSelector"] = node_selector
    body = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": pod_name,
            "labels": {
                LABEL_DEPLOYMENT: dep.name,
                LABEL_SERVICE: svc_name,
            },
        },
        "spec": spec,
    }
    body["metadata"]["labels"][LABEL_HASH] = _template_hash(body)
    return body


def render_headless_service(dep: GraphDeployment) -> Dict[str, Any]:
    """Headless service named after the deployment: gives every pod the
    ``<pod>.<deployment>.<ns>.svc`` DNS name its group coordinator env
    points at (the role StatefulSet DNS plays for the reference's
    multinode groups)."""
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": dep.name,
            "labels": {LABEL_DEPLOYMENT: dep.name},
        },
        "spec": {
            "clusterIP": "None",
            "selector": {LABEL_DEPLOYMENT: dep.name},
            "ports": [{"port": DEFAULT_COORD_PORT, "name": "coord"}],
            # Host pods must resolve the coordinator BEFORE anyone is
            # "ready" (jax.distributed blocks startup on it) — the same
            # reason StatefulSet/LWS publish not-ready addresses.
            "publishNotReadyAddresses": True,
        },
    }


class PodConnector:
    """Drive one GraphDeployment's pods through the kube API."""

    # Pods outlive the operator process: an operator restart must NOT tear
    # down the cluster's workloads (only CR deletion does). The operator
    # consults this on its own shutdown path.
    survives_restart = True

    def __init__(
        self,
        client: KubeClient,
        deployment: GraphDeployment,
        *,
        k8s_namespace: str = "default",
    ) -> None:
        self.client = client
        self.deployment = deployment
        self.k8s_namespace = k8s_namespace
        self._last_counts: Dict[str, int] = {}
        self._conflicts: Dict[str, int] = {}  # pod name → consecutive 409s

    # -- connector surface (mirrors ProcessConnector) ----------------------

    def counts(self) -> Dict[str, int]:
        """Ready replica counts from the last reconcile's observation."""
        return dict(self._last_counts)

    async def apply_counts(
        self, desired: Dict[str, int], *, reason: str = ""
    ) -> None:
        dep = self.deployment
        await self._ensure_service()
        observed = await self.client.list_core(
            self.k8s_namespace, "pods",
            label_selector=f"{LABEL_DEPLOYMENT}={dep.name}",
        )
        by_name = {p["metadata"]["name"]: p for p in observed}

        want: Dict[str, Dict[str, Any]] = {}
        groups: Dict[str, List[str]] = {}  # group key → member pod names
        for svc_name, svc in dep.services.items():
            n = desired.get(svc_name, svc.replicas)
            H = max(svc.hosts_per_replica, 1)
            for r in range(n):
                members = []
                for h in range(H):
                    pod = render_pod(dep, svc_name, svc, r, h)
                    want[pod["metadata"]["name"]] = pod
                    members.append(pod["metadata"]["name"])
                if H > 1:
                    groups[f"{svc_name}/{r}"] = members

        # Multihost group atomicity (the Grove/LWS semantic, and what
        # jax.distributed requires — a lone restarted pod can never rejoin
        # a running coordinator world): if ANY pod of a group is missing,
        # Failed, or drifted, restart the WHOLE group together.
        group_restart: set = set()
        for key, members in groups.items():
            if not any(m in by_name for m in members):
                continue  # first-time creation, nothing to restart
            for m in members:
                pod = by_name.get(m)
                phase = (pod.get("status") or {}).get("phase", "") if pod else ""
                drifted = (
                    pod is not None
                    and pod["metadata"].get("labels", {}).get(LABEL_HASH)
                    != want[m]["metadata"]["labels"][LABEL_HASH]
                )
                if pod is None or drifted or phase in ("Failed", "Succeeded"):
                    group_restart.update(members)
                    logger.info(
                        "multihost group %s restarting as a unit (%s %s)",
                        key, m,
                        "missing" if pod is None
                        else "drifted" if drifted else phase,
                    )
                    break

        # Delete: gone-from-spec, template drift, terminal phase, or a
        # member of a group being restarted as a unit.
        deleted = set()
        for name, pod in list(by_name.items()):
            phase = (pod.get("status") or {}).get("phase", "")
            desired_pod = want.get(name)
            drifted = (
                desired_pod is not None
                and pod["metadata"].get("labels", {}).get(LABEL_HASH)
                != desired_pod["metadata"]["labels"][LABEL_HASH]
            )
            if (
                desired_pod is None or drifted
                or phase in ("Failed", "Succeeded")
                or name in group_restart
            ):
                logger.info(
                    "deleting pod %s (%s)", name,
                    "scale-down" if desired_pod is None
                    else "template-drift" if drifted
                    else "group-restart" if name in group_restart
                    else f"phase={phase}",
                )
                try:
                    await self.client.delete_core(
                        self.k8s_namespace, "pods", name
                    )
                except KubeApiError as exc:
                    if exc.status != 404:
                        raise
                deleted.add(name)

        # Create what's missing.
        for name, pod in want.items():
            if name in by_name and name not in deleted:
                self._conflicts.pop(name, None)
                continue
            try:
                await self.client.create_core(self.k8s_namespace, "pods", pod)
                self._conflicts.pop(name, None)
            except KubeApiError as exc:
                if exc.status != 409:
                    raise
                if name in deleted:
                    # We deleted this name THIS pass; on a real apiserver
                    # it sits Terminating for its grace period — expected,
                    # the next level-triggered pass recreates it.
                    continue
                # Repeated 409s on a pod our label-filtered list never
                # sees mean a FOREIGN same-name pod owns the name — silent
                # forever without this.
                n = self._conflicts[name] = self._conflicts.get(name, 0) + 1
                if n >= 3:
                    logger.warning(
                        "pod %s: %d consecutive create conflicts — a pod "
                        "outside this deployment's labels owns the name; "
                        "replica will stay down until it is removed",
                        name, n,
                    )

        # Observe ready counts: a replica is ready when every host pod of
        # the group is Running. Re-list only when this pass mutated pods —
        # an idle pass's first list is already the freshest truth (halves
        # steady-state apiserver list load at the default 1s cadence).
        created = [n for n in want if n not in by_name or n in deleted]
        if created or deleted:
            observed = await self.client.list_core(
                self.k8s_namespace, "pods",
                label_selector=f"{LABEL_DEPLOYMENT}={dep.name}",
            )
        # Terminating pods keep phase Running until the kubelet finishes —
        # exclude anything with a deletionTimestamp (and anything this same
        # pass deleted) so ready counts don't briefly over-report to the
        # planner after a group restart or scale-down.
        running = {
            p["metadata"]["name"]
            for p in observed
            if (p.get("status") or {}).get("phase") == "Running"
            and not p["metadata"].get("deletionTimestamp")
            and p["metadata"]["name"] not in deleted
        }
        counts: Dict[str, int] = {}
        for svc_name, svc in dep.services.items():
            n = desired.get(svc_name, svc.replicas)
            H = max(svc.hosts_per_replica, 1)
            ready = 0
            for r in range(n):
                if all(
                    f"{dep.name}-{svc_name}-{r}-{h}" in running
                    for h in range(H)
                ):
                    ready += 1
            counts[svc_name] = ready
        self._last_counts = counts

    async def close(self) -> None:
        """Teardown: delete every pod of this deployment + the headless
        service (CR deletion semantics)."""
        dep = self.deployment
        try:
            pods = await self.client.list_core(
                self.k8s_namespace, "pods",
                label_selector=f"{LABEL_DEPLOYMENT}={dep.name}",
            )
        except KubeApiError:
            return
        for p in pods:
            try:
                await self.client.delete_core(
                    self.k8s_namespace, "pods", p["metadata"]["name"]
                )
            except KubeApiError:
                pass
        try:
            await self.client.delete_core(
                self.k8s_namespace, "services", dep.name
            )
        except KubeApiError:
            pass

    # -- internals ---------------------------------------------------------

    async def _ensure_service(self) -> None:
        # Level-triggered on every pass (409 = already there): an
        # externally deleted service heals like any other object.
        try:
            await self.client.create_core(
                self.k8s_namespace, "services",
                render_headless_service(self.deployment),
            )
        except KubeApiError as exc:
            if exc.status != 409:
                raise
