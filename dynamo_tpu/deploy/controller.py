"""GraphController: reconcile a GraphDeployment onto OS processes.

Reference parity: deploy/operator/internal/controller/
dynamographdeployment_controller.go:110 (Reconcile — drive observed state
to spec: create/scale/restart components, fold in planner-driven replica
changes). The deployment unit here is a supervised subprocess per replica
(ProcessConnector supervision primitives); each reconcile pass:

  1. re-reads planner desired counts from the discovery plane for
     planner_scaled services (the planner→operator loop),
  2. respawns crashed replicas / applies replica changes,
  3. applies restart_id changes as a rolling restart.
"""

from __future__ import annotations

import asyncio
import os
from typing import Any, Dict, Optional

from dynamo_tpu.deploy.spec import GraphDeployment
from dynamo_tpu.planner.connectors import planner_key
from dynamo_tpu.planner.process_connector import ProcessConnector, RoleSpec
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class GraphController:
    def __init__(
        self,
        deployment: GraphDeployment,
        *,
        discovery: Optional[Any] = None,  # planner desired-count source
        reconcile_interval_s: float = 2.0,
        stdout=None,
        connector: Optional[Any] = None,  # actuator override (PodConnector)
    ) -> None:
        self.deployment = deployment
        self.discovery = discovery
        self.reconcile_interval_s = reconcile_interval_s
        env = {**os.environ, **deployment.envs}
        # The actuator is pluggable: local supervised subprocesses by
        # default, cluster pods when the operator hands us a PodConnector
        # (deploy/pod_connector.py) — policy (this reconcile loop) stays
        # identical either way.
        self._connector = connector or ProcessConnector(
            {
                name: RoleSpec(
                    command=svc.resolved_command(),
                    env={**env, **svc.env},
                    grace_period_s=svc.grace_period_s,
                )
                for name, svc in deployment.services.items()
            },
            stdout=stdout,
        )
        self._applied_restart_id = deployment.restart_id
        self._task: Optional[asyncio.Task] = None
        self._stop = asyncio.Event()
        self.reconciles = 0

    # -- reconcile ---------------------------------------------------------

    async def desired_counts(self) -> Dict[str, int]:
        counts = {
            name: svc.replicas for name, svc in self.deployment.services.items()
        }
        if self.discovery is not None:
            try:
                doc = await self.discovery.get(planner_key(self.deployment.namespace))
            except Exception:
                logger.exception("planner desired-count read failed")
                doc = None
            if doc:
                for name, svc in self.deployment.services.items():
                    if svc.planner_scaled and svc.planner_role in doc:
                        counts[name] = int(doc[svc.planner_role])
        return counts

    async def reconcile_once(self) -> Dict[str, int]:
        if hasattr(self._connector, "deployment"):
            # Pod actuator renders from the spec: keep it on the live one
            # (replicas-only CR updates swap self.deployment in place).
            self._connector.deployment = self.deployment
        if self.deployment.restart_id != self._applied_restart_id:
            logger.info(
                "restart id changed (%r → %r): rolling restart",
                self._applied_restart_id, self.deployment.restart_id,
            )
            await self._connector.apply_counts(
                {name: 0 for name in self.deployment.services}, reason="restart"
            )
            self._applied_restart_id = self.deployment.restart_id
        counts = await self.desired_counts()
        await self._connector.apply_counts(counts, reason="reconcile")
        self.reconciles += 1
        return counts

    def status(self) -> Dict[str, Any]:
        """(ref: DynamoGraphDeploymentStatus replicas accounting)"""
        live = self._connector.counts()
        return {
            "name": self.deployment.name,
            "namespace": self.deployment.namespace,
            "services": {
                name: {
                    "desired": svc.replicas,
                    "ready": live.get(name, 0),
                    "planner_scaled": svc.planner_scaled,
                }
                for name, svc in self.deployment.services.items()
            },
            "reconciles": self.reconciles,
        }

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._task is None:
            self._stop.clear()
            self._task = asyncio.get_running_loop().create_task(
                self._run(), name=f"graph-controller:{self.deployment.name}"
            )

    async def _run(self) -> None:
        while not self._stop.is_set():
            try:
                await self.reconcile_once()
            except Exception:
                logger.exception("reconcile failed")
            try:
                await asyncio.wait_for(
                    self._stop.wait(), timeout=self.reconcile_interval_s
                )
            except asyncio.TimeoutError:
                pass

    async def stop(self, *, teardown: bool = True) -> None:
        self._stop.set()
        if self._task is not None:
            await self._task
            self._task = None
        if teardown:
            await self._connector.close()
