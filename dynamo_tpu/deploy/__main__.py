"""Deploy CLI: run a GraphDeployment on this host.

Reference parity: the operator's reconcile loop as a foreground process
(`kubectl apply` → here `python -m dynamo_tpu.deploy apply -f graph.yaml`).

  apply -f graph.yaml     reconcile the deployment until interrupted
  validate -f graph.yaml  parse + validate only
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from dynamo_tpu.deploy.controller import GraphController
from dynamo_tpu.deploy.spec import GraphDeployment
from dynamo_tpu.utils.logging import configure_logging


async def run_apply(args) -> None:
    deployment = GraphDeployment.from_file(args.file)
    discovery = None
    if args.planner_loop:
        from dynamo_tpu.runtime.distributed import DistributedRuntime

        discovery = DistributedRuntime.from_settings().discovery
    controller = GraphController(
        deployment, discovery=discovery, stdout=sys.stderr
    )
    controller.start()
    print(f"controller running: {deployment.name} "
          f"({len(deployment.services)} services)", flush=True)
    try:
        while True:
            await asyncio.sleep(10)
            print(json.dumps(controller.status()), flush=True)
    finally:
        await controller.stop()


async def run_operator(args) -> None:
    """Watch DynamoTpuGraphDeployment(+Request) CRs via the k8s API and
    reconcile them (deploy/k8s_operator.py). In-cluster by default; point
    --apiserver at any API endpoint (e.g. `kubectl proxy`)."""
    from dynamo_tpu.deploy.k8s_client import KubeClient
    from dynamo_tpu.deploy.k8s_operator import K8sGraphOperator

    if args.apiserver:
        client = KubeClient(args.apiserver, token=args.token)
    else:
        client = KubeClient.in_cluster()
    webhook_runner = None
    if args.webhook_port:
        from dynamo_tpu.deploy.webhook import serve as serve_webhook

        webhook_runner = await serve_webhook(
            args.webhook_port, args.tls_cert, args.tls_key
        )
    elector = None
    if args.leader_elect:
        from dynamo_tpu.deploy.leader import LeaderElector

        elector = LeaderElector(client, k8s_namespace=args.k8s_namespace)
    operator = K8sGraphOperator(
        client, k8s_namespace=args.k8s_namespace,
        pod_backend=args.pod_backend,
        leader_elector=elector,
    )
    print(
        f"operator watching {args.k8s_namespace} "
        f"(actuator: {'pods' if args.pod_backend else 'processes'})",
        flush=True,
    )
    try:
        await operator.run()
    finally:
        await operator.stop()
        if webhook_runner is not None:
            await webhook_runner.cleanup()


def main() -> None:
    parser = argparse.ArgumentParser("dynamo-tpu deploy")
    sub = parser.add_subparsers(dest="command", required=True)
    for name in ("apply", "validate"):
        p = sub.add_parser(name)
        p.add_argument("-f", "--file", required=True)
        if name == "apply":
            p.add_argument(
                "--planner-loop", action="store_true",
                help="fold planner desired counts from discovery into "
                "planner_scaled services",
            )
    p = sub.add_parser("operator", help="kubernetes operator mode")
    p.add_argument("--apiserver", default=None,
                   help="API base URL (default: in-cluster config)")
    p.add_argument("--token", default=None)
    p.add_argument("--k8s-namespace", default="default")
    p.add_argument(
        "--pod-backend", action="store_true",
        help="actuate CR replicas as cluster pods (TPU nodeSelector + "
        "multihost DYN_TPU_* groups) instead of node-local subprocesses",
    )
    p.add_argument(
        "--webhook-port", type=int, default=0,
        help="also serve the validating admission webhook on this port "
        "(0 = off; kube requires HTTPS — pass --tls-cert/--tls-key)",
    )
    p.add_argument("--tls-cert", default=None)
    p.add_argument("--tls-key", default=None)
    p.add_argument(
        "--leader-elect", action="store_true",
        help="coordination/v1 Lease leader election: only the holder "
        "reconciles, so replicated operators never double-actuate "
        "(ref operator's --leader-elect)",
    )
    args = parser.parse_args()
    configure_logging()
    if args.command == "operator":
        asyncio.run(run_operator(args))
        return
    if args.command == "validate":
        dep = GraphDeployment.from_file(args.file)
        print(json.dumps({
            "name": dep.name,
            "namespace": dep.namespace,
            "services": {n: s.replicas for n, s in dep.services.items()},
            "valid": True,
        }))
        return
    asyncio.run(run_apply(args))


if __name__ == "__main__":
    main()
