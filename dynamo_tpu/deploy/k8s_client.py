"""Minimal async Kubernetes API client for custom resources.

The environment has no `kubernetes` package, and the operator needs only a
narrow slice of the API: list/watch/create/patch/delete on namespaced
custom resources plus the status subresource. This client speaks that slice
directly over aiohttp — the same REST surface controller-runtime wraps for
the reference's Go operator
(deploy/operator/internal/controller/dynamographdeployment_controller.go:110).

Auth: in-cluster (service-account token + CA bundle) via
:meth:`KubeClient.in_cluster`, or explicit ``base_url``/``token`` — which is
also how tests point it at a fake apiserver.
"""

from __future__ import annotations

import asyncio
import json
import os
import ssl
from typing import Any, AsyncIterator, Dict, List, Optional, Tuple

import aiohttp

from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class KubeApiError(RuntimeError):
    def __init__(self, status: int, body: str) -> None:
        super().__init__(f"kube API error {status}: {body[:300]}")
        self.status = status
        self.body = body


class KubeClient:
    def __init__(
        self,
        base_url: str,
        *,
        token: Optional[str] = None,
        ssl_ctx: Optional[ssl.SSLContext] = None,
    ) -> None:
        self.base_url = base_url.rstrip("/")
        self._token = token
        self._ssl = ssl_ctx
        self._session: Optional[aiohttp.ClientSession] = None

    @classmethod
    def in_cluster(cls) -> "KubeClient":
        """Build from the pod's service-account mount (the standard
        in-cluster config: KUBERNETES_SERVICE_HOST/PORT + token + CA)."""
        host = os.environ["KUBERNETES_SERVICE_HOST"]
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        with open(os.path.join(SA_DIR, "token")) as f:
            token = f.read().strip()
        ctx = ssl.create_default_context(cafile=os.path.join(SA_DIR, "ca.crt"))
        return cls(f"https://{host}:{port}", token=token, ssl_ctx=ctx)

    def _headers(self, extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
        h = {"Accept": "application/json"}
        if self._token:
            h["Authorization"] = f"Bearer {self._token}"
        if extra:
            h.update(extra)
        return h

    async def session(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    # -- custom-resource CRUD ---------------------------------------------

    def _cr_path(
        self, group: str, version: str, namespace: str, plural: str,
        name: Optional[str] = None, subresource: Optional[str] = None,
    ) -> str:
        p = f"/apis/{group}/{version}/namespaces/{namespace}/{plural}"
        if name:
            p += f"/{name}"
        if subresource:
            p += f"/{subresource}"
        return p

    async def _request(
        self, method: str, path: str, *,
        params: Optional[Dict[str, str]] = None,
        body: Optional[Any] = None,
        content_type: str = "application/json",
    ) -> Any:
        sess = await self.session()
        async with sess.request(
            method, self.base_url + path, params=params,
            data=None if body is None else json.dumps(body),
            headers=self._headers({"Content-Type": content_type}),
            ssl=self._ssl,
        ) as resp:
            text = await resp.text()
            if resp.status >= 400:
                raise KubeApiError(resp.status, text)
            return json.loads(text) if text else None

    async def list(
        self, group: str, version: str, namespace: str, plural: str,
    ) -> Tuple[List[Dict[str, Any]], str]:
        """Returns (items, resourceVersion) — the watch bookmark."""
        doc = await self._request(
            "GET", self._cr_path(group, version, namespace, plural)
        )
        return doc.get("items", []), doc.get("metadata", {}).get(
            "resourceVersion", ""
        )

    async def get(self, group, version, namespace, plural, name) -> Dict[str, Any]:
        return await self._request(
            "GET", self._cr_path(group, version, namespace, plural, name)
        )

    async def create(self, group, version, namespace, plural, body) -> Dict[str, Any]:
        return await self._request(
            "POST", self._cr_path(group, version, namespace, plural), body=body
        )

    async def delete(self, group, version, namespace, plural, name) -> None:
        await self._request(
            "DELETE", self._cr_path(group, version, namespace, plural, name)
        )

    async def patch(
        self, group, version, namespace, plural, name, body: Dict[str, Any],
    ) -> Dict[str, Any]:
        """Merge-patch the main resource (spec/metadata). The scaling-adapter
        flow uses this: the planner patches adapter spec.replicas, the
        operator patches the target GraphDeployment's service replicas."""
        return await self._request(
            "PATCH",
            self._cr_path(group, version, namespace, plural, name),
            body=body,
            content_type="application/merge-patch+json",
        )

    async def patch_status(
        self, group, version, namespace, plural, name, status: Dict[str, Any],
    ) -> Dict[str, Any]:
        """Merge-patch the /status subresource (requires the CRD's status
        subresource, which both our CRDs declare)."""
        return await self._request(
            "PATCH",
            self._cr_path(group, version, namespace, plural, name, "status"),
            body={"status": status},
            content_type="application/merge-patch+json",
        )

    # -- core/v1 (pods, services) -----------------------------------------
    #
    # Pods aren't custom resources: they live under /api/v1 rather than
    # /apis/{group}. The pod actuator (deploy/pod_connector.py) drives
    # exactly this slice — list-by-label, create, delete — the same calls
    # controller-runtime issues for the reference operator's child workloads
    # (ref: deploy/operator/internal/controller/
    # dynamographdeployment_controller.go:110).

    def _core_path(
        self, namespace: str, plural: str, name: Optional[str] = None
    ) -> str:
        p = f"/api/v1/namespaces/{namespace}/{plural}"
        if name:
            p += f"/{name}"
        return p

    async def list_core(
        self, namespace: str, plural: str,
        *, label_selector: Optional[str] = None,
    ) -> List[Dict[str, Any]]:
        params = {"labelSelector": label_selector} if label_selector else None
        doc = await self._request(
            "GET", self._core_path(namespace, plural), params=params
        )
        return doc.get("items", [])

    async def create_core(
        self, namespace: str, plural: str, body: Dict[str, Any]
    ) -> Dict[str, Any]:
        return await self._request(
            "POST", self._core_path(namespace, plural), body=body
        )

    async def delete_core(self, namespace: str, plural: str, name: str) -> None:
        await self._request(
            "DELETE", self._core_path(namespace, plural, name)
        )

    async def watch(
        self, group, version, namespace, plural,
        *, resource_version: str = "", timeout_s: float = 30.0,
    ) -> AsyncIterator[Dict[str, Any]]:
        """Stream watch events ({type: ADDED|MODIFIED|DELETED, object: ...})
        as the apiserver emits them (chunked JSON lines). Returns when the
        server closes the watch window — callers re-list + re-watch (the
        standard level-triggered reconcile loop)."""
        sess = await self.session()
        params = {"watch": "true", "timeoutSeconds": str(int(timeout_s))}
        if resource_version:
            params["resourceVersion"] = resource_version
        try:
            async with sess.get(
                self.base_url + self._cr_path(group, version, namespace, plural),
                params=params, headers=self._headers(), ssl=self._ssl,
                timeout=aiohttp.ClientTimeout(total=timeout_s + 10),
            ) as resp:
                if resp.status >= 400:
                    raise KubeApiError(resp.status, await resp.text())
                buf = b""
                async for chunk in resp.content.iter_any():
                    buf += chunk
                    while b"\n" in buf:
                        line, buf = buf.split(b"\n", 1)
                        if line.strip():
                            yield json.loads(line)
        except asyncio.TimeoutError:
            return
