"""Checkpoint CR fulfiller: warm the restart tiers for a model identity.

Reference parity: deploy/operator/api/v1alpha1/dynamocheckpoint_types.go +
deploy/chrek — the reference builds a CRIU process-image tar in a Job; the
TPU-native warm-restart tiers are (a) quantized weights in the tmpfs/disk
weight cache (models/weight_cache.py — measured cold 39.7 s → warm 7.0 s
restart, bench/restart.py) and (b) the persistent jax compile cache. This
job materializes tier (a) for the named identity so any later worker of
that identity starts warm, cluster-driven via the Checkpoint CRD.
"""

from __future__ import annotations

import asyncio
import os
from typing import Any, Dict

from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

DEFAULT_CACHE_DIR = os.environ.get(
    "DYN_TPU_WEIGHT_CACHE", "/dev/shm/dynamo_tpu_weights"
)


def _build_and_save(identity: Dict[str, Any], cache_dir: str) -> str:
    from dynamo_tpu.models import llama
    from dynamo_tpu.models.weight_cache import save_params
    from dynamo_tpu.worker.__main__ import BUILTIN_CONFIGS

    model = identity.get("model") or "tiny"
    if model not in BUILTIN_CONFIGS:
        raise ValueError(
            f"unknown model {model!r} (builtin: {sorted(BUILTIN_CONFIGS)})"
        )
    config = BUILTIN_CONFIGS[model]()
    quant = identity.get("quantization")
    key = f"ckpt-{model}-{quant or 'fp'}"

    import jax

    params = llama.init_params(config, jax.random.PRNGKey(0))
    if quant == "int8":
        from dynamo_tpu.models.quantize import quantize_params

        params, _ = quantize_params(params, llama.param_logical_axes(config))
    import numpy as np

    host = jax.tree_util.tree_map(lambda a: np.asarray(a), params)
    return save_params(cache_dir, key, host)


async def run_checkpoint_job(
    identity: Dict[str, Any], cache_dir: str = DEFAULT_CACHE_DIR
) -> str:
    """Build the identity's weights (builtin config; real deployments point
    model at a checkpoint dir handled by hf_loader+weight_cache) and stash
    them in the warm tier. Returns the cache path (CR status.location)."""
    return await asyncio.get_event_loop().run_in_executor(
        None, _build_and_save, identity, cache_dir
    )
