"""Checkpoint CR fulfiller: warm the restart tiers for a model identity.

Reference parity: deploy/operator/api/v1alpha1/dynamocheckpoint_types.go +
deploy/chrek — the reference builds a CRIU process-image tar in a Job; the
TPU-native warm-restart tiers are (a) quantized weights in the tmpfs/disk
weight cache (models/weight_cache.py — measured cold 39.7 s → warm 7.0 s
restart, bench/restart.py) and (b) the persistent jax compile cache. This
job materializes tier (a) through the SAME loader path workers use
(load_checkpoint_cached — same fingerprint key, same shm/disk tiers), so a
Ready Checkpoint CR means the identity's next worker start is a cache hit,
cluster-driven via the Checkpoint CRD.
"""

from __future__ import annotations

import asyncio
import os
from typing import Any, Dict, Optional

from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _warm(identity: Dict[str, Any], shm_dir: Optional[str],
          cache_dir: Optional[str]) -> str:
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.models import weight_cache as wc

    model_dir = identity.get("modelDir")
    if not model_dir or not os.path.isdir(model_dir):
        # Builtin-config workers random-init in the engine — there is no
        # weight artifact to warm, so a Ready status would be a lie.
        raise ValueError(
            "identity.modelDir must name a checkpoint directory; workers "
            "load through load_checkpoint_cached(model_dir, ...) and only "
            "that path has warm tiers (builtin-name identities random-init)"
        )
    config = ModelConfig.from_model_dir(model_dir)
    quant = identity.get("quantization") or None
    kwargs: Dict[str, Any] = {"quantization": quant}
    if cache_dir:
        kwargs["cache_dir"] = cache_dir
    if shm_dir is not None:
        kwargs["shm_dir"] = shm_dir
    _params, hit = wc.load_checkpoint_cached(model_dir, config, **kwargs)
    tier = shm_dir if shm_dir is not None else wc.SHM_CACHE_DIR
    location = tier or kwargs.get("cache_dir", wc.DEFAULT_CACHE_DIR)
    logger.info(
        "checkpoint warm for %s (%s): %s", model_dir,
        "already cached" if hit else "ingested", location,
    )
    return location


async def run_checkpoint_job(
    identity: Dict[str, Any],
    shm_dir: Optional[str] = None,
    cache_dir: Optional[str] = None,
) -> str:
    """Ingest the identity's checkpoint through the worker loader path,
    populating the shm + disk weight tiers under the loader's own
    fingerprint key. Returns the warm-tier path (CR status.location)."""
    return await asyncio.get_running_loop().run_in_executor(
        None, _warm, identity, shm_dir, cache_dir
    )
