"""Physical KV block pool: hash ↔ device-block-id, prefix reuse, LRU.

Reference parity: the G1 (device) pool of KVBM
(lib/llm/src/block_manager/pool/managed.rs — active/inactive sets, reuse &
eviction) fused with the mocker's KvManager semantics (kv_manager.rs:50).
Unlike the mock engine, blocks here name *physical slots* in the HBM cache
arrays, so the pool is the single source of truth for which device block
holds which content hash.

States: free (uninitialized/evicted) → active-private (being filled by one
sequence) → committed (full block, content-hashed, shareable) → inactive
(committed, refcount 0, LRU-evictable) → free.

Emits the same KvEvent stream as the mock engine for router indexing.
"""

from __future__ import annotations

from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional, Sequence, Tuple

from dynamo_tpu.engines.mock.kv_manager import EventCallback, KvEvent


@dataclass
class _Committed:
    block_id: int
    parent_hash: Optional[int]
    ref_count: int = 0


class BlockPool:
    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        *,
        on_event: Optional[EventCallback] = None,
    ) -> None:
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._on_event = on_event
        self._free: Deque[int] = deque(range(num_blocks))
        self._by_hash: Dict[int, _Committed] = {}
        self._lru: "OrderedDict[int, _Committed]" = OrderedDict()  # hash → entry

    # -- stats -------------------------------------------------------------

    @property
    def free_blocks(self) -> int:
        return len(self._free) + len(self._lru)

    @property
    def cached_blocks(self) -> int:
        return len(self._lru)

    @property
    def active_blocks(self) -> int:
        return self.num_blocks - self.free_blocks

    @property
    def usage(self) -> float:
        return self.active_blocks / self.num_blocks if self.num_blocks else 0.0

    def bytes_breakdown(self, block_bytes: int) -> Dict[str, int]:
        """Structural byte accounting for the HBM ledger / GET
        /debug/memory: pool-state block counts × per-block KV bytes. The
        pool itself is the single source of truth for which physical
        blocks hold live vs reusable-cached vs free content, so this is
        the only place the split can be computed without tearing."""
        block_bytes = int(block_bytes)
        return {
            "active_bytes": self.active_blocks * block_bytes,
            "cached_bytes": self.cached_blocks * block_bytes,
            "free_bytes": len(self._free) * block_bytes,
            "total_bytes": self.num_blocks * block_bytes,
        }

    # -- prefix reuse ------------------------------------------------------

    def contains(self, block_hash: int) -> bool:
        """Whether a committed block with this content hash is resident."""
        return block_hash in self._by_hash

    def snapshot_committed(self):
        """Pin EVERY committed block and return
        [(hash, parent_hash, block_id)] — a stable view for checkpointing.
        The caller must release(ids, hashes) (aligned) when done."""
        out = []
        for h, entry in self._by_hash.items():
            if entry.ref_count == 0:
                self._lru.pop(h, None)
            entry.ref_count += 1
            out.append((h, entry.parent_hash, entry.block_id))
        return out

    def committed_view(self) -> List[Tuple[int, Optional[int]]]:
        """Read-only [(hash, parent_hash)] of every committed block, in
        insertion order (parents always commit before children, so replaying
        this list rebuilds a radix index). Used by KV-event re-sync."""
        return [(h, e.parent_hash) for h, e in self._by_hash.items()]

    def match_prefix(self, block_hashes: Sequence[int]) -> int:
        n = 0
        for h in block_hashes:
            if h in self._by_hash:
                n += 1
            else:
                break
        return n

    def pin_prefix(self, block_hashes: Sequence[int]) -> Tuple[int, List[int]]:
        """Pin the longest cached prefix; returns (matched_blocks, their ids)."""
        matched = self.match_prefix(block_hashes)
        ids: List[int] = []
        for h in block_hashes[:matched]:
            entry = self._by_hash[h]
            if entry.ref_count == 0:
                self._lru.pop(h, None)
            entry.ref_count += 1
            ids.append(entry.block_id)
        return matched, ids

    # -- allocation --------------------------------------------------------

    def alloc(self) -> Optional[int]:
        """Take one free physical block (evicting cold cache if needed)."""
        if self._free:
            return self._free.popleft()
        if self._lru:
            h, entry = self._lru.popitem(last=False)
            del self._by_hash[h]
            self._emit(KvEvent(kind="removed", block_hashes=[h]))
            return entry.block_id
        return None

    def commit(
        self, block_id: int, block_hash: int, parent_hash: Optional[int]
    ) -> None:
        """A sequence finished filling `block_id`; register it shareable.

        If the hash is already cached (another sequence computed the same
        content), the physical block stays private to its owner — it is
        returned to the free list on release instead of double-registering.
        """
        if block_hash in self._by_hash:
            return
        self._by_hash[block_hash] = _Committed(
            block_id=block_id, parent_hash=parent_hash, ref_count=1
        )
        self._emit(
            KvEvent(kind="stored", block_hashes=[block_hash], parent_hash=parent_hash)
        )

    def release(self, block_ids: Sequence[int], block_hashes: Sequence[int]) -> None:
        """Sequence done. `block_hashes[i]` pairs with `block_ids[i]` for the
        committed prefix; remaining ids are private/partial blocks → freed."""
        owned = set()
        for i, h in enumerate(block_hashes):
            entry = self._by_hash.get(h)
            if entry is not None and entry.block_id == block_ids[i]:
                owned.add(i)
                entry.ref_count -= 1
                if entry.ref_count <= 0:
                    entry.ref_count = 0
                    self._lru[h] = entry
                    self._lru.move_to_end(h)
        for i, bid in enumerate(block_ids):
            if i not in owned:
                self._free.append(bid)

    def clear(self) -> None:
        """Drop all reusable cached blocks (ref: clear_kv_blocks route)."""
        evicted = list(self._lru)
        for h in evicted:
            entry = self._lru.pop(h)
            del self._by_hash[h]
            self._free.append(entry.block_id)
        if evicted:
            self._emit(KvEvent(kind="removed", block_hashes=evicted))
        self._emit(KvEvent(kind="cleared"))

    def _emit(self, event: KvEvent) -> None:
        if self._on_event is not None:
            self._on_event(event)
