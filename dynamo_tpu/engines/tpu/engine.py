"""JaxEngine: continuous batching over a jit-compiled paged-KV model.

Reference parity: this is the framework's flagship backend, playing the role
vLLM plays behind components/src/dynamo/vllm (continuous batching, paged KV,
prefix caching, KV events, chunked prefill) — but TPU-native:

  - ONE jitted step function (model forward_paged + fused sampling) serves
    prefill (B=1, C=chunk) and decode (B=max_num_seqs, C=1). Shapes are
    bucketed (powers of two for chunk length and block-table width) so XLA
    compiles a handful of programs, then everything is cache hits.
  - KV cache = two [L, num_blocks, block_size, KH, D] arrays in HBM, donated
    through every step (XLA updates in place). Physical blocks are leased by
    block_pool.BlockPool with prefix reuse + LRU eviction and KV events.
  - All device work runs on a single executor thread so the asyncio serving
    loop never blocks on compiles or device sync.
  - Preemption-by-recompute when the pool is exhausted mid-decode (the
    youngest sequence releases its blocks and re-queues), like vLLM's
    recompute preemption.
"""

from __future__ import annotations

import asyncio
import collections
import functools
import math
import socket
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engines.mock.kv_manager import KvEvent
from dynamo_tpu.engines.tpu.block_pool import BlockPool
from dynamo_tpu.engines.tpu.runner import DeviceRunner, _next_pow2
from dynamo_tpu.engines.tpu.tick_budget import (
    BUDGET_STATE_OFF,
    TickBudgetConfig,
    TickBudgeter,
)
from dynamo_tpu.llm.protocols.common import (
    BackendOutput,
    FinishReason,
    PreprocessedRequest,
    TokenLogprob,
)
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.ops.sampling import compute_logprobs, sample_tokens
from dynamo_tpu.parallel.mesh import AxisNames
from dynamo_tpu.parallel.sharding import ShardingRules, param_shardings, shard_params
from dynamo_tpu.runtime import fault_names
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.faults import fault_point, note_activity
from dynamo_tpu.runtime.device_observe import (
    FlightRecorder,
    HbmLedger,
    dump_flight,
    tree_device_bytes,
)
from dynamo_tpu.tokens.blocks import adapter_salt, compute_block_hashes
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class JaxEngineArgs:
    """Engine knobs (ref: vllm EngineArgs surface used by
    components/src/dynamo/vllm/args.py — block size, gpu blocks, max seqs)."""

    config: ModelConfig = field(default_factory=ModelConfig)
    block_size: int = 16
    num_kv_blocks: int = 512
    max_num_seqs: int = 8
    max_model_len: int = 1024
    prefill_chunk: int = 512  # max tokens per prefill step (chunked prefill)
    watermark: float = 0.01
    # Admission backpressure (overload armor): refuse NEW admissions while
    # pool occupancy (active blocks only — reusable cached blocks don't
    # count) is at or past this fraction and sequences are running.
    # Admitting into a near-full pool doesn't serve the request faster —
    # it trades one queued request for a preemption storm that re-prefills
    # running ones. 1.0 disables (the pre-PR 8 behavior).
    admit_kv_high_watermark: float = 0.95
    # Batched prefill: pack up to this many admissions into ONE device
    # dispatch ([Bp, C] with per-row start/len). B=1 prefill wastes the MXU
    # (measured: B=8 costs only ~1.4× B=1 on a v5e) and serial admission was
    # the round-2 bench's bottleneck (64-slot engine ramping 4 seqs/tick).
    prefill_batch: int = 8
    admit_batches_per_tick: int = 8  # bounds decode stall per scheduler tick
    enable_prefix_caching: bool = True
    use_kernel: Optional[bool] = None  # None = auto (pallas on TPU)
    seed: int = 0
    # Multi-LoRA: directory of PEFT adapters (lora/source.py layout). All
    # adapters are stacked and served from one compiled program
    # (ops/lora.py); requests select theirs via PreprocessedRequest.lora_name.
    lora_dir: Optional[str] = None
    # Fused decode iterations per dispatch (llama.decode_multi). Dispatch
    # latency dominates small-model decode on TPU; stop conditions are
    # evaluated host-side at this granularity (overshoot discarded).
    decode_steps: int = 8
    # Speculative decoding: "ngram" = prompt-lookup proposals (no draft
    # model) verified in ONE [B, spec_k+1]-token dispatch. Greedy-only — a
    # tick with sampling/logprobs/processor requests falls back to the
    # fused decode path. Wins latency on extractive/repetitive outputs.
    spec_mode: Optional[str] = None
    spec_ngram: int = 3  # match length for the prompt-lookup proposal
    spec_k: int = 4  # proposed tokens per verify dispatch
    # Weight quantization: "int8" = per-channel weight-only int8
    # (ops/quant.py) — halves weight HBM, 8B-class models fit one v5e chip
    # (the reference's FP8/NVFP4-checkpoint deployment lever, TPU-style).
    quantization: Optional[str] = None
    # Static top-N width compiled into the logprobs decode programs
    # (OpenAI caps top_logprobs at 20). Per-request counts trim at emit;
    # the logprob-free programs never pay for it.
    top_logprobs_cap: int = 20
    # KV cache layout: per-layer 4D pools (tuple of [NB, BS, KH, D]) instead
    # of one stacked 5D array. The layered form lets XLA update each pool in
    # place; the stacked form forces the layer-scan to rematerialize the FULL
    # cache as scan ys every step (~2× cache size of HBM traffic — measured
    # 22.2 → 15.2 ms/step at the bench shape). Stacked remains for
    # pipeline-parallel stages that slice the layer axis.
    layered_cache: bool = True
    # KV-cache quantization: "int8" = per-token-per-head dynamic int8 pools
    # (ops/kv_quant.py) — halves the decode step's history-read bytes AND
    # the decode kernel's page VMEM (batch_block 8 → 16), and doubles the
    # sequences a fixed HBM budget can hold. The reference's
    # kv_cache_dtype=fp8 engine lever, TPU-style. Requires layered_cache.
    kv_cache_dtype: Optional[str] = None
    # Fused-layer decode megakernel (ops/pallas/fused_layer.py): one pallas
    # program per layer streaming int8 weights with the attention page
    # fetches overlapped. None = auto (TPU + int8 weights + layered bf16
    # cache + eligible architecture). The XLA path stays the fallback for
    # every ineligible shape and for prefill.
    use_megakernel: Optional[bool] = None
    # Decode-tick pipelining: how many fused decode bursts may be in flight
    # on the device at once. 2 (default) double-buffers — burst N+1 is
    # dispatched from the device-resident carry while the host reads back
    # and emits burst N, hiding readback RTT + emit/scheduler work behind
    # device compute. 1 = fully synchronous (dispatch, read, emit, repeat).
    # Token/logprob streams are bit-identical across depths for a fixed
    # seed: sampling noise is keyed on (seed, sequence salt, token index),
    # never dispatch order (docs/design_docs/decode_pipelining.md).
    # spec_mode caps the effective depth at 1 (prompt-lookup proposals
    # need reconciled host tokens at every burst boundary).
    pipeline_depth: int = 2
    # SLA-driven intra-chip prefill/decode split (tick_budget.py): when
    # enabled, the static admit_batches_per_tick cap is replaced by a
    # closed-loop per-tick prefill TOKEN budget that shrinks when decode
    # ITL burns the SLO error budget and grows back when it has headroom
    # (docs/design_docs/disagg_serving.md, "intra-chip middle mode").
    # Off by default: aggregated mode, today's behavior byte-for-byte.
    tick_budget_enabled: bool = False
    # Starvation floor / ceiling in prefill tokens per tick. None derives
    # floor = prefill_chunk (one chunk round always lands, bounding TTFT)
    # and ceiling = admit_batches_per_tick × prefill_chunk (the static
    # cap's worst-case single-tick prefill spend).
    tick_budget_floor_tokens: Optional[int] = None
    tick_budget_ceiling_tokens: Optional[int] = None
    # Policy knob: where between floor (0.0, strict ITL) and ceiling
    # (1.0, max throughput) the budget starts.
    tick_budget_policy: float = 0.5
    # Decode-phase ITL SLO driving the budgeter's internal burn estimate;
    # None = the budget only moves via an external burn source or the
    # overload ladder's squeeze.
    tick_budget_itl_slo_s: Optional[float] = None

    @property
    def max_blocks_per_seq(self) -> int:
        return math.ceil(self.max_model_len / self.block_size)


@dataclass
class _Sequence:
    request: PreprocessedRequest
    context: Context
    queue: "asyncio.Queue[Optional[BackendOutput]]"
    prompt: List[int]
    all_tokens: List[int]  # prompt + generated
    generated: List[int] = field(default_factory=list)
    block_ids: List[int] = field(default_factory=list)
    block_hashes: List[int] = field(default_factory=list)  # committed prefix
    slot: int = -1
    next_token: int = 0  # decode input token
    logprob_pending: Optional[float] = None
    admission_failures: int = 0  # deterministic per-request errors (poisoned)
    hash_salt: int = 0  # adapter ⊕ multimodal content salt (prefix cache)
    # Sampling-RNG salt (arrival order): the sequence's noise stream is
    # keyed (engine seed, salt, token index) — survives preemption and is
    # independent of slot/batch/dispatch placement.
    salt: int = 0
    # Speculative prompt-lookup: n-gram → position AFTER its last occurrence
    # (incrementally indexed up to ngram_upto).
    ngram_index: Dict[tuple, int] = field(default_factory=dict)
    ngram_upto: int = 0
    # Live-handoff drain: position snapshot taken when the sequence is
    # detached from its slot (= len(all_tokens) - 1 at the reconciled
    # boundary); also the resume position an adopted sequence installs at.
    detach_pos: int = -1
    # Trajectory-plane phase boundaries (time.monotonic stamps; 0 = never
    # reached). Stamped OUTSIDE the decode tick — at enqueue, admission,
    # first streamed output, and detach — and folded into retrospective
    # engine.queue/prefill/decode spans when the stream ends, so the hot
    # loop itself never touches span machinery.
    t_enqueue: float = 0.0
    t_prefill_start: float = 0.0
    t_first_out: float = 0.0
    t_detached: float = 0.0
    # KV-reuse attribution (runtime/kv_reuse_observe.py): the tier this
    # request's prefix hit resolved from and the ROI dict stamped at
    # admission (cached/recomputed tokens, estimated seconds saved).
    kv_hit_tier: str = "device"
    kv_roi: Optional[Dict[str, Any]] = None
    # Speculative onboard lease (kvbm/manager.py KvPrefetch), started at
    # enqueue from the router's prefix hint. Admission joins and claims
    # it; abort/shed revokes it (the pinned blocks fall back to cache).
    kv_prefetch: Optional[Any] = None


@dataclass
class _InflightBurst:
    """One dispatched-but-unreaped decode burst (pipelined decode tick).
    ``seqs`` snapshots (slot, sequence) at dispatch time; at reap, a row is
    emitted only if its slot still holds the SAME sequence — rows whose
    sequence finished in an earlier burst while this one was in flight are
    dropped (their device-side writes landed in the 2-burst lookahead
    blocks that were reserved at dispatch, so they corrupt nothing)."""

    handles: Any  # runner._DecodeHandles
    seqs: List[Tuple[int, _Sequence]]
    t_dispatch: float
    occupancy: int
    # Perf-ledger attribution stamps (runtime/perf_ledger.py), taken at
    # dispatch so the reap can feed the ledger without recomputing shape:
    # width bucket + program variant key the fingerprint sentinel judges
    # on; dispatch host cost, mean context, and the host gap this burst
    # paid before its dispatch.
    nb_bucket: int = 0
    variant: str = ""
    dispatch_s: float = 0.0
    avg_ctx: float = 0.0
    gap_s: float = 0.0


# Block-table lookahead reserved by every decode dispatch, in bursts of
# ``decode_steps`` tokens. Constant 2 at EVERY pipeline depth — the
# speculative burst can never outrun its reservation, and depth 1 and
# depth 2 request pool blocks at identical points in the reap order, which
# is what makes preemption decisions (and therefore full token streams)
# depth-independent (docs/design_docs/decode_pipelining.md).
PIPELINE_LOOKAHEAD_BURSTS = 2


def table_width_bucket(max_blocks: int, cap: int) -> int:
    """Pow2 bucket for a dispatched block-table width, clamped to the
    engine's per-sequence table capacity. Every distinct width is a
    separate compiled decode program — the megakernel's dynamic page loop
    makes the TRACE width-independent, but XLA still specializes on the
    operand shape — so bucketing bounds the program count to ~log2(cap)
    as contexts grow instead of one program per context length. Shared by
    the decode tick and the speculative-verify dispatch (spec.py)."""
    return min(_next_pow2(max(max_blocks, 1)), cap)


@dataclass
class _ProcPrep:
    """Per-request logits-processor parameters (ops/logits_process.py).
    Present only when the request actually uses a processor — absence keeps
    the engine on the processor-free compiled programs."""

    minp: float
    rep: float
    pres: float
    freq: float
    bias_ids: np.ndarray  # [MAX_BIAS_SLOTS] int32
    bias_vals: np.ndarray  # [MAX_BIAS_SLOTS] float32


@dataclass
class _Prep:
    """Admission bookkeeping produced by _prepare_admission."""

    ids: List[int]
    hashes: List[int]
    matched: int
    matched_tokens: int
    sp: Tuple[float, int, float]
    adapter_id: int
    mm_embeds: Optional[np.ndarray]
    mm_slot_of: Optional[np.ndarray]
    procs: Optional[_ProcPrep] = None


class JaxEngine:
    """AsyncEngine over the native JAX model."""

    def __init__(
        self,
        args: JaxEngineArgs,
        params: Optional[Any] = None,
        *,
        mesh: Optional[jax.sharding.Mesh] = None,
        rules: Optional[ShardingRules] = None,
        on_kv_event: Optional[Callable[[KvEvent], None]] = None,
        topology: Optional[Any] = None,  # parallel/multihost.HostTopology
        runner: Optional[DeviceRunner] = None,
    ) -> None:
        self.args = args
        self.config = args.config
        self.mesh = mesh
        self.rules = rules or ShardingRules()
        self.pool = BlockPool(
            args.num_kv_blocks, args.block_size, on_event=on_kv_event
        )
        # All device state (params, LoRA stacks, KV caches, RNG, compiled
        # programs, sleep transitions) lives in the DeviceRunner; this class
        # owns scheduling policy only. A pre-built runner may be injected
        # (multihost leader shares construction with followers).
        self.runner = runner or DeviceRunner(
            args, params, mesh=mesh, rules=self.rules, topology=topology,
        )
        self._use_kernel = self.runner.use_kernel
        # Sleep/wake orchestration (ref: vllm handlers.py sleep :286 /
        # wake_up :317 — RL weight-sync workflows park the engine to free
        # accelerator memory). 0 = awake; 1 = KV freed; 2 = weights too.
        self._sleep_requested: Optional[int] = None
        self._sleep_inflight = False
        self._sleep_event = asyncio.Event()
        self.spec_proposed = 0
        self.spec_accepted = 0
        # Brownout lever (runtime/overload.py): under pressure speculative
        # decode burns decode ticks on rejected proposals — the overload
        # controller suspends it without tearing down the engine.
        self._spec_suspended = False
        # Requests shed at admission dequeue because their deadline had
        # already expired (observability; bench reads the activity
        # counter, tests read this).
        self.deadline_sheds = 0
        # Live-handoff drain plane (runtime/drain.py): while draining, new
        # generate() calls refuse with a typed migratable error, admission
        # holds, and the DrainController detaches/exports live decodes.
        # Detach requests and adoptions are serviced by the scheduler loop
        # behind its drain barrier (the only place slot state may mutate
        # with bursts reconciled).
        self._draining = False
        self._detach_requests: "collections.deque" = collections.deque()
        self._adoptions: "collections.deque[_Sequence]" = collections.deque()
        # Sequences in an in-flight admission batch (popped from _waiting,
        # slot not yet taken) — adopt_handoff counts them or it promises a
        # peer capacity the batch is about to install into.
        self._admitting = 0
        self.handoffs_exported = 0
        self.handoffs_adopted = 0
        # SLA-driven prefill/decode tick split (engines/tpu/tick_budget.py).
        # _pending_prefill: a budget-paused joint prefill parked at a chunk
        # boundary (blocks pinned, rows keep progress) — it resumes ahead
        # of any new admission. _tick_budget_left: this tick's remaining
        # prefill token grant (None = unbudgeted), decremented by the
        # admitter's chunk rounds.
        self._budgeter: Optional[TickBudgeter] = None
        if args.tick_budget_enabled:
            floor = args.tick_budget_floor_tokens
            if floor is None:
                floor = args.prefill_chunk
            ceiling = args.tick_budget_ceiling_tokens
            if ceiling is None:
                ceiling = max(
                    floor, args.admit_batches_per_tick * args.prefill_chunk
                )
            self._budgeter = TickBudgeter(
                TickBudgetConfig(
                    floor_tokens=int(floor),
                    ceiling_tokens=int(ceiling),
                    policy=args.tick_budget_policy,
                    itl_slo_s=args.tick_budget_itl_slo_s,
                ),
                on_event=self._record_budget_event,
            )
        self._pending_prefill: Optional[Any] = None
        self._tick_budget_left: Optional[int] = None

        S = args.max_num_seqs
        self._slots: List[Optional[_Sequence]] = [None] * S
        self._pos = np.zeros(S, dtype=np.int32)  # tokens resident in cache
        self._block_tables = np.zeros(
            (S, args.max_blocks_per_seq), dtype=np.int32
        )
        self._temp = np.ones(S, dtype=np.float32)
        self._topk = np.zeros(S, dtype=np.int32)
        self._topp = np.ones(S, dtype=np.float32)
        self._adapter_ids = np.zeros(S, dtype=np.int32)
        self._tok_mirror = np.zeros(S, dtype=np.int32)  # decode input token
        self._salts = np.zeros(S, dtype=np.int32)  # per-slot sampling salt
        self._next_salt = 0  # arrival-order salt counter
        # Dirty-slot tracking for the device-resident decode state: the
        # numpy arrays above are the scheduler's VIEW; the device copies in
        # DeviceRunner.slot_state are reconciled incrementally at the next
        # dispatch for exactly the slots a mutating event touched
        # (admission, finish, preempt, spec emission → _dirty_state; block
        # append / table rewrite → _dirty_tables). Invariant: a slot with a
        # LIVE sequence is only ever state-dirty while no burst is in
        # flight (mutating events either happen at reap — where the dirty
        # row deactivates a finished slot — or behind a drain barrier).
        self._dirty_state: set = set(range(S))
        self._dirty_tables: set = set(range(S))
        # Pipelined decode: dispatched-but-unreaped bursts, oldest first.
        self._inflight: "collections.deque[_InflightBurst]" = (
            collections.deque()
        )
        self.preemptions = 0
        self._t_last_ready: Optional[float] = None  # last burst readback
        # Per-slot logits-processor params (neutral unless the occupant asks).
        from dynamo_tpu.ops.logits_process import MAX_BIAS_SLOTS

        self._minp = np.zeros(S, dtype=np.float32)
        self._rep = np.ones(S, dtype=np.float32)
        self._pres = np.zeros(S, dtype=np.float32)
        self._freq = np.zeros(S, dtype=np.float32)
        self._bias_ids = np.full((S, MAX_BIAS_SLOTS), -1, dtype=np.int32)
        self._bias_vals = np.zeros((S, MAX_BIAS_SLOTS), dtype=np.float32)
        self._uses_procs = np.zeros(S, dtype=bool)

        self.kvbm: Optional[Any] = None  # TieredKvManager (kvbm/manager.py)
        # Plain deque (+ wake event), NOT an asyncio.Queue: _requeue must
        # push preempted sequences to the FRONT, and the round-1 approach of
        # swapping in a fresh Queue raced concurrent generate() calls that
        # held the old object (requests lost forever).
        self._waiting: "collections.deque[_Sequence]" = collections.deque()
        self._loop_task: Optional[asyncio.Task] = None
        self._stopped = asyncio.Event()
        self._failure: Optional[str] = None  # terminal engine failure
        self._consecutive_tick_failures = 0
        # Consecutive failed admission attempts across ALL requests; resets
        # on any success. Catches systemic admission failure (e.g. a broken
        # prefill program) without letting a few poisoned requests brick the
        # engine.
        self._admission_failure_streak = 0
        self._wake = asyncio.Event()
        self._executor = ThreadPoolExecutor(1, thread_name_prefix="jax-engine")
        # Transfer lane: HBM→host readbacks for disagg/offload run here so
        # they never occupy the device-executor thread between decode ticks
        # (VERDICT r4 item 4 — transfers must overlap decode, the role of
        # the reference's async offload engine).
        self._transfer_executor = ThreadPoolExecutor(
            1, thread_name_prefix="jax-engine-transfer"
        )
        self.steps = 0  # decode iterations (observability)
        self.prefill_tokens = 0
        self.generated_tokens = 0
        # Step-loop metric families (registered on the system server by
        # attach_engine; dependency-free, so always on).
        from dynamo_tpu.engines.metrics import EngineStepMetrics

        self.step_metrics = EngineStepMetrics()
        # Perf ledger (runtime/perf_ledger.py): always-on per-shape decode
        # attribution + the live regression sentinel. Process-global — the
        # status server renders/serves the same instance — with this
        # engine's identity (fingerprint key) and a roofline closure over
        # its model config installed here. configure() also loads any
        # persisted fingerprints (corrupt file → counted cold start).
        from dynamo_tpu.runtime.perf_ledger import global_perf_ledger
        from dynamo_tpu.runtime.roofline import make_roofline_fn

        self._perf = global_perf_ledger()
        try:
            perf_backend = jax.default_backend()
        except Exception:
            perf_backend = "unknown"
        self._perf.configure(
            preset=self.config.name,
            backend=perf_backend,
            host=socket.gethostname(),
            roofline_fn=make_roofline_fn(self.config, args.quantization),
        )

        # Device-plane observability (runtime/device_observe.py):
        # - flight: the tick loop's single-writer event ring (admit,
        #   preempt, dispatch, reap, spec tick, KV transfers, abort). The
        #   runner owns a second ring for device-thread events; the system
        #   server merges both at GET /debug/flight.
        # - hbm: structural byte ledger over live device state, sampled at
        #   scrape/snapshot time only (never on the tick path).
        self.flight = FlightRecorder("engine")
        # Trajectory-plane clock-domain label for this engine's phase
        # spans; None = the process service label (worker mains set it,
        # multi-engine test harnesses give each engine its own).
        self.trace_proc: Optional[str] = None
        runner = self.runner
        self.hbm = HbmLedger()
        self.hbm.register(
            "kv_cache",
            lambda: tree_device_bytes((runner.k_cache, runner.v_cache)),
        )
        self.hbm.register("params", lambda: tree_device_bytes(runner.params))
        self.hbm.register(
            "slot_state", lambda: tree_device_bytes(runner.slot_state)
        )
        self.hbm.register(
            "slot_tables", lambda: tree_device_bytes(runner.slot_tables)
        )
        self.hbm.register("lora", lambda: tree_device_bytes(runner.lora))
        self.hbm.register(
            "proc_state", lambda: tree_device_bytes(runner.proc_state)
        )

        self._last_flight_dump = float("-inf")  # abort-dump rate limiter

        # stats() snapshot: the system-server thread scrapes stats while
        # the tick loop mutates _slots/_inflight/pool counters — a live
        # read can tear (kv_usage from before a reap, inflight_bursts from
        # after). The loop REPLACES this dict wholesale at reap/admission/
        # idle boundaries; readers get one consistent generation.
        self._stats_cache: Optional[Dict[str, Any]] = None

    # -- device-state delegates (DeviceRunner owns the mechanism) ---------

    @property
    def params(self):
        return self.runner.params

    @property
    def _k_cache(self):
        return self.runner.k_cache

    @property
    def _v_cache(self):
        return self.runner.v_cache

    @property
    def _host_params(self):
        return self.runner.host_params

    @property
    def _lora_index(self) -> Dict[str, int]:
        return self.runner.lora_index

    def load_lora(self, name: str, adapter_dir: str) -> None:
        """Load one adapter at runtime (ref: vllm handlers.py LoRA load
        :453). Changing the stack shape recompiles the decode program on the
        next step — acceptable for an administrative operation."""
        if name in self.runner.lora_index:
            raise ValueError(f"LoRA adapter {name!r} already loaded")
        from dynamo_tpu.engines.tpu.runner import _adapter_to_host
        from dynamo_tpu.lora import load_lora_adapter

        adapter = _adapter_to_host(
            load_lora_adapter(adapter_dir, self.config, name=name)
        )
        adapter.name = name
        self.runner.install_adapter(adapter)

    def unload_lora(self, name: str) -> None:
        """Unload by name. In-flight sequences using the adapter keep their
        (now zeroed) slot — they degrade to base-model output rather than
        crash; new requests naming it are rejected at admission."""
        if name not in self.runner.lora_index:
            # KeyError (not ValueError): the admin surface maps it to 404
            # while ValueError means conflict (409) on the load side.
            raise KeyError(f"LoRA adapter {name!r} is not loaded")
        self.runner.remove_adapter(name)

    def lora_names(self) -> List[str]:
        return sorted(self.runner.lora_index)

    def _pipeline_depth(self) -> int:
        # Speculative decoding caps the effective depth at 1: every spec
        # tick needs fully-reconciled host tokens to propose from, and a
        # pipelined fallback would advance 2 bursts between proposal
        # points — halving the lookup cadence and skipping right over
        # n-gram matches. Spec is itself a latency path; it keeps the
        # synchronous tick it was tuned for. A brownout-suspended spec
        # engine decodes on the fused path and gets its pipelining back.
        if self.args.spec_mode and not self._spec_suspended:
            return 1
        return max(1, int(getattr(self.args, "pipeline_depth", 1) or 1))

    def _dispatch_on_device(self, nb, want_logprobs, want_procs,
                            state_sync, table_sync):
        """Device-thread half of a burst dispatch: reconcile dirty slot
        rows into the device-resident state, then enqueue the burst."""
        if state_sync is not None:
            self.runner.sync_slots(*state_sync)
        if table_sync is not None:
            self.runner.sync_tables(*table_sync)
        return self.runner.decode_dispatch(
            nb, want_logprobs=want_logprobs, use_procs=want_procs
        )

    def _run_step(
        self, tokens, start_pos, chunk_lens, block_tables, temp, topk, topp,
        adapter_ids, mm_embeds=None, mm_slot=None, procs=None, want_top=False,
        first_chunk=False, salts=None,
    ):
        """One prefill step on the device thread (blocking). See
        DeviceRunner.run_step; kept as an engine method so tests can inject
        faults by monkeypatching it."""
        return self.runner.run_step(
            tokens, start_pos, chunk_lens, block_tables, temp, topk, topp,
            adapter_ids, mm_embeds=mm_embeds, mm_slot=mm_slot, procs=procs,
            want_top=want_top, first_chunk=first_chunk, salts=salts,
        )

    async def _device(self, fn, *a):
        return await asyncio.get_running_loop().run_in_executor(
            self._executor, fn, *a
        )

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        if self._loop_task is None:
            self._loop_task = asyncio.get_running_loop().create_task(
                self._scheduler_loop(), name="jax-engine-scheduler"
            )

    async def stop(self) -> None:
        self._stopped.set()
        self._wake.set()
        if self._loop_task is not None:
            await self._loop_task
            self._loop_task = None
        self._executor.shutdown(wait=False)
        self._transfer_executor.shutdown(wait=False)
        # Clean shutdown persists the perf fingerprints this run earned;
        # after a terminal tick failure the windows describe a degraded
        # engine, and a degraded baseline is worse than none.
        if self._failure is None:
            self._perf.store_fingerprints()

    def stats(self) -> Dict[str, Any]:
        """Engine stats for /engine/stats and metric scrapes. While the
        scheduler loop is running, returns the snapshot it published at
        the last reap/admission boundary (see _publish_stats) — a cross-
        thread caller can never observe kv_usage and inflight_bursts from
        different tick generations. With no loop running (tests, stopped
        engine) the state is quiescent and computed live."""
        task = self._loop_task
        if task is not None and not task.done() and self._stats_cache is not None:
            return dict(self._stats_cache)
        return self._compute_stats()

    def _publish_stats(self) -> None:
        self._stats_cache = self._compute_stats()

    def _compute_stats(self) -> Dict[str, Any]:
        out = {
            "active_seqs": sum(1 for s in self._slots if s is not None),
            "waiting": len(self._waiting),
            "kv_usage": self.pool.usage,
            "free_blocks": self.pool.free_blocks,
            "cached_blocks": self.pool.cached_blocks,
            "total_blocks": self.args.num_kv_blocks,
            "decode_steps": self.steps,
            "prefill_tokens": self.prefill_tokens,
            "generated_tokens": self.generated_tokens,
            "sleep_level": self._sleep_level,
            "pipeline_depth": self._pipeline_depth(),
            "inflight_bursts": len(self._inflight),
            "preemptions": self.preemptions,
            # Drain plane: rides load reports so KvScheduler stops placing
            # new work here the moment the report lands.
            "draining": 1 if self._draining else 0,
            # Overload plane inputs: queue depth + the admission refusal
            # watermark ride load reports router-ward (LoadSnapshot), and
            # deadline sheds are the proof expired work never prefilled.
            "queue_depth": len(self._waiting),
            "kv_high_watermark": self.args.admit_kv_high_watermark,
            "deadline_sheds": self.deadline_sheds,
            # Tick-budget plane (engines/tpu/tick_budget.py): the
            # EFFECTIVE per-tick prefill budget and the chunk size ride
            # stats() into LoadSnapshot and the engine gauge family, so a
            # silent budget collapse shows as its own signal instead of
            # masquerading as an unexplained TTFT regression. Budgeter
            # off (aggregated mode) reports 0 / state OFF.
            "prefill_budget_tokens": (
                self._budgeter.budget_tokens
                if self._budgeter is not None else 0
            ),
            "budget_state": (
                self._budgeter.state
                if self._budgeter is not None else BUDGET_STATE_OFF
            ),
            "prefill_chunk_tokens": self.args.prefill_chunk,
            "budget_rollovers": (
                self._budgeter.rollovers
                if self._budgeter is not None else 0
            ),
            # Megakernel coverage: decode bursts on the fused path vs the
            # XLA fallback (per-variant split nested — flattens into
            # per-variant gauges on the metrics surface), plus per-key
            # demotions. A demotion shifts bursts from fused to fallback
            # HERE, so it can never masquerade as a plain perf regression.
            "mk_fused_bursts": self.runner.mk_fused_bursts,
            "mk_fallback_bursts": self.runner.mk_fallback_bursts,
            "mk_demoted_variants": len(self.runner._mk_demoted_keys),
            "mk_bursts_by_variant": dict(self.runner.mk_bursts_by_variant),
        }
        if self.args.spec_mode:
            out["spec_proposed"] = self.spec_proposed
            out["spec_accepted"] = self.spec_accepted
        if self.kvbm is not None:
            out["kvbm"] = self.kvbm.stats()
        return out

    @property
    def num_total_blocks(self) -> int:
        return self.args.num_kv_blocks

    def kv_pool_bytes_breakdown(self) -> Dict[str, int]:
        """Pool-state KV byte split (active/cached/free × per-block bytes)
        for GET /debug/memory — the HBM ledger's kv_cache category is the
        allocation's total footprint; this is how much of it holds live vs
        reusable vs dead content."""
        total = tree_device_bytes((self.runner.k_cache, self.runner.v_cache))
        per_block = total // max(self.args.num_kv_blocks, 1)
        return self.pool.bytes_breakdown(per_block)

    def clear_kv_blocks(self) -> int:
        """Flush the reusable prefix cache (ref: clear_kv_blocks.rs route).
        In-flight sequences keep their pinned blocks."""
        n = self.pool.cached_blocks
        self.pool.clear()
        return n

    # -- sleep / wake ------------------------------------------------------

    @property
    def sleep_level(self) -> int:
        return self.runner.sleep_level

    _sleep_level = property(lambda self: self.runner.sleep_level)

    async def sleep(self, level: int = 1) -> None:
        """Park the engine to free device memory (ref: vllm handlers.py
        sleep :286). Level 1 frees the KV cache; level 2 also offloads the
        weights to host RAM. Active sequences drain first; queued requests
        wait until wake()."""
        if self._sleep_level > 0:
            return
        if int(level) >= 2 and self.runner.multihost:
            # Validate HERE, not in the tick: a failure after the request is
            # queued would leave the sleep() caller awaiting an event that
            # never fires.
            raise RuntimeError(
                "sleep level 2 (weight offload) is unsupported in multihost "
                "mode; use level 1"
            )
        await self.start()
        if self._failure is not None or (
            self._loop_task is None or self._loop_task.done()
        ):
            raise RuntimeError(
                "engine scheduler is not running; cannot sleep "
                f"(failure: {self._failure})"
            )
        self._sleep_requested = max(1, min(2, int(level)))
        self._sleep_event.clear()
        self._wake.set()
        await self._sleep_event.wait()

    async def wake(self) -> None:
        """Restore device state after sleep (ref: vllm wake_up :317)."""
        if (
            self._sleep_level == 0
            and self._sleep_requested is None
            and not self._sleep_inflight
        ):
            return
        self._sleep_requested = None
        await self._device(self._do_wake)
        self.flight.record("wake")
        self._publish_stats()
        # Release a sleep() caller whose request we just cancelled.
        self._sleep_event.set()
        self._wake.set()

    def _do_sleep(self, level: int) -> None:
        # Device frees only — BlockPool (and its KV-event callback, which
        # touches asyncio state) is cleared on the event-loop thread in
        # _sleep_tick, per the engine's threading contract.
        self.runner.sleep_device(level)

    def _do_wake(self) -> None:
        self.runner.wake_device()

    # -- AsyncEngine -------------------------------------------------------

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[BackendOutput]:
        await self.start()
        if self._draining:
            # Typed, MIGRATABLE refusal: the router stops placing work here
            # the moment the draining load report lands, but a request that
            # raced the report must bounce fast so the frontend's Migration
            # re-dispatches it to a serving worker (the "typed requeue"
            # rung of the drain ladder).
            from dynamo_tpu.runtime.drain import WorkerDrainingError

            raise WorkerDrainingError(
                "worker is draining; re-dispatch to another instance"
            )
        if isinstance(request, dict):
            request = PreprocessedRequest.from_dict(request)
        prompt = list(request.token_ids)
        if not prompt:
            yield BackendOutput(error="empty prompt", finish_reason=FinishReason.ERROR)
            return
        if len(prompt) >= self.args.max_model_len:
            yield BackendOutput(
                error=(
                    f"prompt length {len(prompt)} exceeds max_model_len "
                    f"{self.args.max_model_len}"
                ),
                finish_reason=FinishReason.ERROR,
            )
            return
        # Paged prefill needs every prompt block plus one decode block
        # resident at once: a prompt larger than the whole pool can never
        # be admitted, and admission would requeue it forever (pool-dry
        # looks transient from where it sits). Refuse it typed instead.
        n_prompt_blocks = math.ceil(len(prompt) / self.args.block_size)
        if n_prompt_blocks + 1 > self.args.num_kv_blocks:
            yield BackendOutput(
                error=(
                    f"prompt needs {n_prompt_blocks} KV blocks + 1 for "
                    f"decode, but the pool only has "
                    f"{self.args.num_kv_blocks}"
                ),
                finish_reason=FinishReason.ERROR,
            )
            return
        if self._failure is not None:
            yield BackendOutput(
                error=f"engine failed: {self._failure}",
                finish_reason=FinishReason.ERROR,
            )
            return
        if request.lora_name and request.lora_name not in self._lora_index:
            yield BackendOutput(
                error=(
                    f"unknown LoRA adapter {request.lora_name!r} "
                    f"(loaded: {self.lora_names()})"
                ),
                finish_reason=FinishReason.ERROR,
            )
            return
        seq = _Sequence(
            request=request,
            context=context,
            queue=asyncio.Queue(),
            prompt=prompt,
            all_tokens=list(prompt),
            # Arrival-order RNG salt: the sequence's sampling noise is
            # (seed, salt, token index), so its stream is identical no
            # matter which slot/burst/pipeline depth serves it.
            salt=self._next_salt,
        )
        self._next_salt = (self._next_salt + 1) & 0x7FFFFFFF
        seq.t_enqueue = time.monotonic()
        self._waiting.append(seq)
        self._maybe_prefetch(seq)
        self._wake.set()
        try:
            async for out in self._stream_outputs(seq):
                if seq.t_first_out == 0.0 and out.token_ids:
                    seq.t_first_out = time.monotonic()
                yield out
        finally:
            # A stream that ends before admission claimed its lease
            # (client abort, early error) must release the pinned blocks;
            # after a claim this is a no-op.
            self._revoke_prefetch(seq, "aborted")
            self._export_phase_spans(seq)

    def _maybe_prefetch(self, seq: _Sequence) -> None:
        """Speculative onboarding (docs/design_docs/kv_prefetch.md): the
        router ships its radix-match prediction as
        ``estimated_prefix_hit_blocks``; when the hint is positive, start
        the G2/G3→G1 onboard walk NOW so it overlaps this request's queue
        wait (and the batch ahead of it) instead of serializing inside
        admission. No hint — cold traffic, no router, or a multimodal
        salt we cannot compute before admission unpacks the embeds —
        means no walk: unrouted traffic never pays a speculation tax."""
        if self.kvbm is None or not self.args.enable_prefix_caching:
            return
        hint = int(getattr(seq.request, "estimated_prefix_hit_blocks", 0) or 0)
        if hint <= 0:
            return
        if (seq.request.extra or {}).get("mm_embeds"):
            return
        try:
            seq.hash_salt = adapter_salt(seq.request.lora_name)
            hashes = compute_block_hashes(
                seq.prompt, self.args.block_size, salt=seq.hash_salt
            )
            if not hashes or self.pool.match_prefix(hashes) >= len(hashes):
                return  # fully device-resident already: nothing to onboard
            seq.kv_prefetch = self.kvbm.prefetch(hashes)
        except Exception:
            # Speculation is optional: a prefetch-setup bug costs the
            # overlap, never the request (admission onboards serially).
            logger.debug("speculative prefetch setup failed", exc_info=True)

    def _revoke_prefetch(self, seq: _Sequence, reason: str) -> None:
        pf = seq.kv_prefetch
        if pf is not None:
            seq.kv_prefetch = None
            pf.revoke(reason)

    def _export_phase_spans(self, seq: _Sequence) -> None:
        """Retrospective engine.queue / engine.prefill / engine.decode
        spans for one finished stream (trajectory plane). Built once per
        request from the monotonic stamps the serving path already took —
        nothing here runs inside the decode tick, and requests outside any
        trace cost one dict lookup."""
        if not seq.context.baggage.get("traceparent"):
            return
        try:
            from dynamo_tpu.utils.tracing import export_span

            proc = getattr(self, "trace_proc", None)
            end = time.monotonic()
            t_admit = seq.t_prefill_start or seq.t_first_out or end
            export_span(
                "engine.queue", seq.context,
                start_mono=seq.t_enqueue or t_admit, end_mono=t_admit,
                proc=proc,
            )
            if seq.t_prefill_start:
                roi = seq.kv_roi or {}
                export_span(
                    "engine.prefill", seq.context,
                    start_mono=seq.t_prefill_start,
                    end_mono=seq.t_first_out or end,
                    proc=proc, prompt_tokens=len(seq.prompt),
                    cached_tokens=roi.get("cached_tokens"),
                    prefill_seconds_saved=roi.get("seconds_saved"),
                )
            if seq.t_first_out:
                # A handed-off stream's decode ends at detach — the relay
                # gap is the drain plane's handoff_stall, and the peer's
                # own decode span covers the continuation.
                export_span(
                    "engine.decode", seq.context,
                    start_mono=seq.t_first_out,
                    end_mono=seq.t_detached or end,
                    proc=proc, generated=len(seq.generated),
                    handed_off=bool(seq.t_detached) or None,
                )
        except Exception:
            logger.debug("phase-span export failed", exc_info=True)

    async def _stream_outputs(
        self, seq: _Sequence
    ) -> AsyncIterator[BackendOutput]:
        """Drain a sequence's output queue to its consumer. An exception
        object on the queue RAISES out of the stream — the drain plane's
        fallback ladder uses this to surface a typed migratable error
        (handoff failed / worker draining) through the serving handler so
        the frontend's Migration re-dispatches the request."""
        while True:
            out = await seq.queue.get()
            if out is None:
                return
            if isinstance(out, BaseException):
                raise out
            yield out
            if out.finish_reason is not None:
                return

    # -- scheduler ---------------------------------------------------------

    async def _scheduler_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                if self._sleep_requested is not None or self._sleep_level > 0:
                    if await self._sleep_tick():
                        continue
                # Drain plane: detaches and adoptions mutate slot state, so
                # they ride the same reconciled boundary admission does —
                # every in-flight burst reaped first.
                if self._detach_requests or self._adoptions:
                    await self._drain_inflight()
                    self._service_drain_queues()
                # Admission installs into slots and allocates pool blocks —
                # both must see fully-reconciled state, so drain the
                # pipeline first. Gated on a free slot actually existing:
                # under saturation (queue deep, every slot busy) the
                # admission attempt is doomed and the pipeline keeps
                # flowing instead of degrading to depth 1.
                if self._inflight and (
                    self._pending_prefill is not None
                    or (
                        self._waiting
                        and any(s is None for s in self._slots)
                    )
                ):
                    await self._drain_inflight()
                admitted = False
                if self._budgeter is not None:
                    # Budgeted admission (tick_budget.py): the closed-loop
                    # prefill token grant replaces the static batch cap.
                    admitted = await self._admit_tick_budgeted()
                else:
                    # Admit in batched prefill dispatches; a per-tick batch
                    # cap bounds how long running decodes stall behind
                    # prefill (chunked-prefill fairness, like the
                    # reference schedulers).
                    for _ in range(self.args.admit_batches_per_tick):
                        if await self._admit_batch() == 0:
                            break
                        admitted = True
                if admitted:
                    # Prefill just ran on the device: the wait before the
                    # next decode dispatch is device-busy time, not
                    # host-injected gap — don't observe it.
                    self._t_last_ready = None
                    self._publish_stats()
                active = (
                    any(s is not None for s in self._slots)
                    or bool(self._inflight)
                )
                if active:
                    if self.args.spec_mode == "ngram" and not self._spec_suspended:
                        if not await self._spec_tick():
                            await self._decode_tick()
                    else:
                        await self._decode_tick()
                elif not admitted:
                    # Idle: request inter-arrival time is not host gap.
                    self._t_last_ready = None
                    if self._budgeter is not None:
                        # The next reap's inter-reap gap would span the
                        # idle period — don't let it testify as ITL.
                        self._budgeter.note_idle()
                    self._publish_stats()
                    self._wake.clear()
                    try:
                        await asyncio.wait_for(self._wake.wait(), timeout=0.05)
                    except asyncio.TimeoutError:
                        pass
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                from dynamo_tpu.runtime.network.spmd_channel import (
                    SpmdChannelError,
                )

                if isinstance(exc, SpmdChannelError):
                    # A follower died: the SPMD worker group is beyond
                    # repair (the follower missed ops; every process must
                    # issue every global program). Fail FAST — no retries —
                    # so the supervisor restarts the whole group.
                    logger.error("SPMD channel broke: failing worker: %s", exc)
                    self._fail_terminally(exc)
                    break
                # A failed tick may leave dispatched-but-unreaped bursts
                # whose device carry ran ahead of what was emitted: drop
                # them and resync from the host mirrors — the retried
                # bursts regenerate identical tokens (position-keyed RNG).
                self._abort_inflight()
                # Retry with exponential backoff (transient device hiccups
                # can span seconds), then treat the failure as terminal: fail
                # every pending request and refuse new ones. Round 1 retried
                # a missing-kernel ModuleNotFoundError forever and hung the
                # bench for its whole timeout (VERDICT weak #1).
                self._consecutive_tick_failures += 1
                logger.exception(
                    "jax engine scheduler tick failed (%d consecutive)",
                    self._consecutive_tick_failures,
                )
                if self._consecutive_tick_failures >= 5:
                    self._fail_terminally(exc)
                    break
                await asyncio.sleep(
                    min(0.05 * 2 ** self._consecutive_tick_failures, 2.0)
                )
            else:
                self._consecutive_tick_failures = 0
                if self._failure is not None:  # systemic admission failure
                    break
        # Shutdown: in-flight results are dropped (every surviving sequence
        # is about to be finished with CANCELLED/ERROR anyway).
        self._inflight.clear()
        reason = (
            FinishReason.ERROR if self._failure is not None else FinishReason.CANCELLED
        )
        err = f"engine failed: {self._failure}" if self._failure else None
        # A budget-parked prefill never installed: release its pinned
        # blocks and route its rows through the same shutdown path as the
        # waiting queue below.
        self._unpark_pending()
        for seq in self._slots:
            if seq is not None:
                if err:
                    seq.queue.put_nowait(
                        BackendOutput(error=err, finish_reason=reason)
                    )
                    self._finish(seq, reason, emit=False)
                else:
                    self._finish(seq, reason)
        while self._waiting:
            seq = self._waiting.popleft()
            seq.queue.put_nowait(BackendOutput(error=err, finish_reason=reason))
        # Drain-plane stragglers: unresolved detach requests surface as an
        # error (the controller falls back down its ladder); adopted-but-
        # uninstalled sequences release their blocks and end their streams.
        while self._detach_requests:
            _rid, fut = self._detach_requests.popleft()
            if not fut.done():
                fut.set_exception(
                    RuntimeError("engine stopped during handoff detach")
                )
        while self._adoptions:
            seq = self._adoptions.popleft()
            self.pool.release(seq.block_ids, seq.block_hashes)
            seq.queue.put_nowait(BackendOutput(error=err, finish_reason=reason))
        self._publish_stats()

    def _fail_terminally(self, exc: Exception) -> None:
        self._failure = f"{type(exc).__name__}: {exc}"
        logger.critical(
            "jax engine entering failed state: %s "
            "(tick strikes=%d, admission streak=%d)",
            self._failure,
            self._consecutive_tick_failures,
            self._admission_failure_streak,
        )

    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self._slots):
            if s is None:
                return i
        return None

    # -- admission (policy in engines/tpu/admission.py) --------------------
    # Thin delegates keep the engine surface stable (tests monkeypatch
    # these names for fault injection) while the pipeline lives in one
    # dedicated module.

    @property
    def _admitter(self):
        if self.__dict__.get("_admitter_obj") is None:
            from dynamo_tpu.engines.tpu.admission import Admitter

            self.__dict__["_admitter_obj"] = Admitter(self)
        return self.__dict__["_admitter_obj"]

    async def _admit_batch(self) -> int:
        if self._draining:
            # Draining: the controller sheds the waiting queue with typed
            # requeue errors; admitting one more prefill would just create
            # another live stream to hand off.
            return 0
        if self._pending_prefill is not None:
            # A budget-paused batch holds the admission pipeline: it must
            # resume (in FIFO order, at its chunk boundary) before
            # anything new dequeues.
            return 0
        return await self._admitter._admit_batch()

    async def _admit_tick_budgeted(self) -> bool:
        """Budgeted admission phase (engines/tpu/tick_budget.py): resume
        a parked prefill first, then admit new batches until this tick's
        prefill token grant is spent. Replaces the static
        admit_batches_per_tick cap; a tick with no decode work to protect
        gets an unbounded grant. Returns True when prefill ran."""
        budgeter = self._budgeter
        decode_active = (
            any(s is not None for s in self._slots) or bool(self._inflight)
        )
        self._tick_budget_left = budgeter.tick_grant(decode_active)
        admitted = False
        try:
            if self._pending_prefill is not None:
                if self._draining:
                    # The drain plane owns the queue now: the parked batch
                    # returns whole (typed-requeue rung, nothing half-
                    # installed).
                    self._unpark_pending()
                    return False
                await self._continue_pending()
                admitted = True  # the resume ran chunk rounds on-device
                if self._pending_prefill is not None:
                    return True  # grant spent; still parked
            while (
                self._tick_budget_left is None or self._tick_budget_left > 0
            ):
                n = await self._admit_batch()
                if n:
                    admitted = True
                if self._pending_prefill is not None or n == 0:
                    break
        finally:
            left = self._tick_budget_left
            self._tick_budget_left = None
            if left is not None:
                if left < 0:
                    # The last chunk round overdrew the grant (rounds are
                    # atomic): pay it back from the next tick's budget.
                    budgeter.add_debt(-left)
                elif (
                    left > 0
                    and decode_active
                    and self._waiting
                    and self._pending_prefill is None
                    and not self._draining
                ):
                    # Admission held with budget unspent (KV watermark,
                    # pool dry, slots full): the grant rolls into decode —
                    # the tick proceeds at full cadence instead of idling
                    # (the PR 8 + budgeter double-stall hazard).
                    budgeter.note_rollover(left)
        return admitted

    async def _continue_pending(self) -> int:
        """Resume the parked prefill's chunk rounds under the current
        grant; Admitter._run_prefill re-parks, installs, or containment-
        ejects. Returns rows installed."""
        pending = self._pending_prefill
        self._pending_prefill = None
        return await self._admitter._run_prefill(pending)

    def _unpark_pending(self) -> None:
        """Return a parked prefill batch to the waiting queue whole:
        release its pinned blocks, requeue rows in arrival order. Used by
        drain begin and engine shutdown — already-prefilled chunks are
        recomputed on re-admission (the same recompute contract as
        preemption, so streams stay bit-identical)."""
        pending = self._pending_prefill
        if pending is None:
            return
        self._pending_prefill = None
        for seq, prep in reversed(pending.batch):
            self.pool.release(prep.ids, prep.hashes[: prep.matched])
            self._requeue(seq)
        self.flight.record("prefill_unpark", rows=len(pending.batch))

    def _record_budget_event(self, kind: str, **fields) -> None:
        """Flight-ring seam for the tick budgeter and the admission pause
        path: the engine stays the ring's single writer (DYN005)."""
        self.flight.record(kind, **fields)

    def set_budget_pressure(self, on: bool) -> None:
        """Overload-ladder first rung (runtime/overload.py): squeeze the
        per-tick prefill budget to the starvation floor / release it.
        Cheaper than clamping max_tokens or shedding, so the ladder fires
        it first and releases it last. No-op without a budgeter."""
        if self._budgeter is None:
            return
        self._budgeter.set_pressure(bool(on))
        self._wake.set()

    async def _finish_admission(self, batch) -> int:
        return await self._admitter._finish_admission(batch)

    def _contain_admission_failure(self, seqs, exc: Exception) -> None:
        self._admitter._contain_admission_failure(seqs, exc)

    async def _prepare_admission(self, seq: _Sequence):
        return await self._admitter._prepare_admission(seq)

    async def _prefill_batch(self, batch):
        return await self._admitter._prefill_batch(batch)

    def _install(self, seq: _Sequence, prep, slot: int, first_token: int,
                 first_logprob: float, first_top=None) -> None:
        self._admitter._install(
            seq, prep, slot, first_token, first_logprob, first_top
        )
        self.flight.record(
            "admit", request_id=seq.request.request_id, slot=slot,
            prompt=len(seq.prompt), cached_blocks=prep.matched,
        )

    def _sampling_of(self, req: PreprocessedRequest) -> Tuple[float, int, float]:
        return self._admitter._sampling_of(req)

    def _procs_of(self, req: PreprocessedRequest):
        return self._admitter._procs_of(req)

    def _requeue(self, seq: _Sequence) -> None:
        seq.block_ids = []
        seq.block_hashes = []
        self._waiting.appendleft(seq)

    def set_spec_suspended(self, suspended: bool) -> None:
        """Brownout lever: park/restore speculative decode without
        touching the engine args (runtime/overload.py wires this to the
        healthy↔brownout transitions). Takes effect at the next tick;
        in-flight proposals finish normally."""
        suspended = bool(suspended)
        if suspended == self._spec_suspended:
            return
        self._spec_suspended = suspended
        if self.args.spec_mode:
            self.flight.record("spec_suspend", on=suspended)
        self._wake.set()

    def _shed_expired(self, seq: _Sequence) -> None:
        """Finish a waiting sequence that stopped BEFORE admission. A
        deadline expiry is a typed, client-visible error (the request's
        budget is gone — admitting it would burn prefill on work nobody
        is waiting for); a plain cancellation stays a quiet CANCELLED."""
        self._revoke_prefetch(seq, "shed")
        if seq.context.stop_reason == "deadline":
            self.deadline_sheds += 1
            note_activity("deadline_expired")
            self.flight.record(
                "deadline_shed", request_id=seq.request.request_id,
                queued_s=round(seq.context.elapsed, 3),
            )
            seq.queue.put_nowait(
                BackendOutput(
                    error="deadline expired before admission "
                    "(shed at dequeue, no prefill spent)",
                    error_kind="timeout",
                    finish_reason=FinishReason.ERROR,
                )
            )
        else:
            seq.queue.put_nowait(
                BackendOutput(finish_reason=FinishReason.CANCELLED)
            )

    async def _sleep_tick(self) -> bool:
        """Handle a pending sleep request / asleep state. Returns True when
        this tick is consumed (the main loop should ``continue``)."""
        if self._sleep_level > 0:  # asleep: idle until wake() or stop()
            self._publish_stats()
            self._wake.clear()
            try:
                await asyncio.wait_for(self._wake.wait(), timeout=0.05)
            except asyncio.TimeoutError:
                pass
            return True
        # Sleep requested but not yet asleep: drain active sequences first
        # (no new admissions), then release device memory.
        if any(s is not None for s in self._slots):
            await self._decode_tick()
            return True
        if self._pending_prefill is not None:
            # A budget-parked prefill must resolve before sleeping —
            # pool.clear() below would free its pinned blocks in place.
            # Finish it unbudgeted (_tick_budget_left is None between
            # ticks); its sequences then drain via the decode branch
            # above on subsequent passes.
            await self._drain_inflight()
            await self._continue_pending()
            return True
        level = self._sleep_requested
        if level is None:  # wake() cancelled the request mid-drain
            return True
        # All sequences have finished; reap any zombie bursts so nothing
        # holds device buffers (or stale carry) across the sleep.
        await self._drain_inflight()
        self._sleep_requested = None
        self.pool.clear()  # on the loop thread: emits 'cleared' to routers
        # _sleep_inflight closes the window where a concurrent wake() sees
        # "not sleeping, nothing requested" while _do_sleep is in flight —
        # it must queue its _do_wake behind us on the device executor.
        self._sleep_inflight = True
        try:
            await self._device(self._do_sleep, level)
        finally:
            self._sleep_inflight = False
        self.flight.record("sleep", level=level)
        self._publish_stats()
        self._sleep_event.set()
        return True

    def _prepare_decode(self, lookahead: int) -> "List[_Sequence]":
        """Shared decode-tick preamble: finish cancelled/overlong sequences
        and ensure every survivor has blocks covering the next ``lookahead``
        positions (preempt-by-recompute when the pool is dry). Returns the
        active sequences."""
        args = self.args
        for slot in range(args.max_num_seqs - 1, -1, -1):
            seq = self._slots[slot]
            if seq is None:
                continue
            if seq.context.stopped:
                self._finish(seq, FinishReason.CANCELLED)
                continue
            pos = int(self._pos[slot])
            if pos >= args.max_model_len:
                self._finish(seq, FinishReason.LENGTH)
                continue
            last_pos = min(
                pos + lookahead - 1, args.max_blocks_per_seq * args.block_size - 1
            )
            need_blocks = last_pos // args.block_size + 1
            while len(seq.block_ids) < need_blocks:
                b = self.pool.alloc()
                if b is None:
                    self._preempt(seq)
                    break
                self._block_tables[slot, len(seq.block_ids)] = b
                seq.block_ids.append(b)
                self._dirty_tables.add(slot)
        return [s for s in self._slots if s is not None]

    # -- speculative decoding (prompt-lookup / n-gram) ---------------------
    # Policy lives in engines/tpu/spec.py (NgramSpecDecoder); the engine
    # keeps the device hook + a lazily built decoder.

    @property
    def _spec(self):
        if self.__dict__.get("_spec_decoder") is None:
            from dynamo_tpu.engines.tpu.spec import NgramSpecDecoder

            self.__dict__["_spec_decoder"] = NgramSpecDecoder(self)
        return self.__dict__["_spec_decoder"]

    def _run_spec(self, tokens, start_pos, chunk_lens, block_tables,
                  adapter_ids, temp=None, topk=None, topp=None):
        return self.runner.run_spec(
            tokens, start_pos, chunk_lens, block_tables, adapter_ids,
            temp=temp, topk=topk, topp=topp,
        )

    def _propose(self, seq: _Sequence) -> List[int]:
        return self._spec.propose(seq)

    def _spec_eligible(self, active: "List[_Sequence]") -> bool:
        return self._spec.eligible(active)

    async def _spec_tick(self) -> bool:
        handled = await self._spec.tick()
        if handled:
            self.flight.record(
                "spec_tick", proposed=self.spec_proposed,
                accepted=self.spec_accepted,
            )
        return handled

    async def _decode_tick(self) -> None:
        """Pipelined decode tick: top the in-flight window up to
        ``pipeline_depth`` bursts, then reap (read back + emit) the oldest.
        At depth 1 this degenerates to dispatch-then-reap — today's fully
        synchronous behavior. At depth 2 the device always has the next
        burst queued while the host overlaps readback, stop-condition
        reconciliation and emission of the previous one."""
        depth = self._pipeline_depth()
        while len(self._inflight) < depth:
            if not await self._dispatch_burst():
                break
        if self._inflight:
            await self._reap_burst()

    def _blocks_shortfall(self, lookahead: int) -> int:
        """How many blocks the next _prepare_decode would need beyond what
        the pool can serve (same per-seq arithmetic, so a non-positive
        shortfall guarantees allocation succeeds without preemption)."""
        args = self.args
        need = 0
        for slot, seq in enumerate(self._slots):
            if seq is None:
                continue
            pos = int(self._pos[slot])
            last_pos = min(
                pos + lookahead - 1,
                args.max_blocks_per_seq * args.block_size - 1,
            )
            need += max(0, last_pos // args.block_size + 1 - len(seq.block_ids))
        return need - self.pool.free_blocks

    async def _dispatch_burst(self) -> bool:
        """Prepare + enqueue one decode burst. Returns False when there is
        nothing to decode. H2D on the steady path is ZERO: slot state and
        tables upload only for dirty slots; tokens/pos ride the device
        carry of the previous burst."""
        args = self.args
        K = args.decode_steps
        lookahead = K * PIPELINE_LOOKAHEAD_BURSTS
        # Preemption drains the pipeline first: if growing the tables could
        # exhaust the pool, reap in-flight bursts (their finishes may free
        # blocks) before letting _prepare_decode preempt — so a preemption
        # decision is only ever taken against fully-reconciled state, at
        # the same reap boundary regardless of pipeline depth.
        while self._inflight and self._blocks_shortfall(lookahead) > 0:
            await self._reap_burst()
        active = self._prepare_decode(lookahead)
        if not active:
            return False

        state_sync = self._build_state_sync()
        table_sync = self._build_table_sync()
        # Width bucket for THIS burst: host pos lags the device carry by K
        # per in-flight burst, so the burst being dispatched spans up to
        # host pos + (inflight + 1) * K — the same bucket a depth-1 engine
        # computes for the same burst index.
        inflight_off = K * len(self._inflight)
        max_blocks = 1
        sum_ctx = 0
        for seq in active:
            ctx = int(self._pos[seq.slot]) + inflight_off + K
            sum_ctx += ctx
            max_blocks = max(
                max_blocks, (ctx - 1) // args.block_size + 1
            )
        nb_bucket = table_width_bucket(max_blocks, args.max_blocks_per_seq)
        want_logprobs = any(
            s.request.sampling.logprobs is not None for s in active
        )
        want_procs = any(self._uses_procs[s.slot] for s in active)
        had_inflight = bool(self._inflight)
        t0 = time.monotonic()
        # Chaos seam, deliberately AFTER the sync payloads were built (the
        # dirty sets are already cleared): recovery must resync every slot
        # from the mirrors (_abort_inflight), and the position-keyed RNG
        # must regenerate identical tokens on the retried burst.
        fault_point(fault_names.ENGINE_TICK_DISPATCH)
        handles = await self._device(
            self._dispatch_on_device, nb_bucket, want_logprobs, want_procs,
            state_sync, table_sync,
        )
        t_dispatched = time.monotonic()
        # Host-gap: how long the device sat idle on host work between the
        # previous burst's readback and this dispatch. When another burst
        # was already in flight the device never waited — observe 0.
        gap = 0.0
        if self._t_last_ready is not None:
            gap = 0.0 if had_inflight else max(
                0.0, t0 - self._t_last_ready
            )
            self.step_metrics.observe_host_gap(gap)
        self.step_metrics.observe_inflight(len(self._inflight) + 1)
        self._inflight.append(
            _InflightBurst(
                handles=handles,
                seqs=[(s.slot, s) for s in active],
                t_dispatch=t0,
                occupancy=len(active),
                nb_bucket=nb_bucket,
                variant=self.runner._variant_label(
                    nb_bucket, want_logprobs, want_procs
                ),
                dispatch_s=t_dispatched - t0,
                avg_ctx=sum_ctx / len(active),
                gap_s=gap,
            )
        )
        self.flight.record(
            "dispatch", nb=nb_bucket, occupancy=len(active),
            inflight=len(self._inflight),
        )
        return True

    def _build_state_sync(self):
        """Payload for DeviceRunner.sync_slots covering the dirty slots
        (None when clean — the steady-state case)."""
        if not self._dirty_state:
            return None
        slots = sorted(self._dirty_state)
        self._dirty_state.clear()
        sl = np.asarray(slots, dtype=np.int64)
        rows = {
            "tokens": self._tok_mirror[sl],
            "pos": self._pos[sl],
            "active": np.asarray(
                [1 if self._slots[s] is not None else 0 for s in slots],
                np.int32,
            ),
            "temp": self._temp[sl],
            "topk": self._topk[sl],
            "topp": self._topp[sl],
            "adapter_ids": self._adapter_ids[sl],
            "salts": self._salts[sl],
            "minp": self._minp[sl],
            "rep": self._rep[sl],
            "pres": self._pres[sl],
            "freq": self._freq[sl],
            "bias_ids": self._bias_ids[sl],
            "bias_vals": self._bias_vals[sl],
        }
        return (slots, rows)

    def _build_table_sync(self):
        if not self._dirty_tables:
            return None
        slots = sorted(self._dirty_tables)
        self._dirty_tables.clear()
        return (slots, self._block_tables[np.asarray(slots, np.int64)].copy())

    async def _reap_burst(self) -> None:
        """Read back + emit the OLDEST in-flight burst. Stop conditions are
        reconciled here: a row whose sequence already finished (in a burst
        reaped while this one was in flight) is dropped — its slot was
        deactivated and its device pos reset by the dirty-slot sync, and
        its speculative KV writes landed in reserved lookahead blocks."""
        # Chaos seam: a reap failure drops an in-flight burst whose device
        # carry ran ahead of emission — the abort path must roll back.
        fault_point(fault_names.ENGINE_TICK_REAP)
        rec = self._inflight.popleft()
        toks, logps, topv, topi = await self._device(
            self.runner.decode_read, rec.handles
        )
        self._t_last_ready = time.monotonic()
        self.steps += 1
        gen0 = self.generated_tokens
        for slot, seq in rec.seqs:
            if self._slots[slot] is not seq or seq.slot != slot:
                continue  # finished/preempted while this burst was in flight
            self._emit_burst(
                seq, toks[slot], logps[slot],
                None if topv is None else topv[slot],
                None if topi is None else topi[slot],
            )
        # Emitted (post-stop-condition) tokens, not dispatched K×B — the
        # honest throughput number the planner divides by step time. The
        # duration is dispatch→readback of THIS burst (queue-inclusive at
        # depth ≥ 2).
        self.step_metrics.observe_decode(
            time.monotonic() - rec.t_dispatch, rec.occupancy,
            self.generated_tokens - gen0,
        )
        if self._budgeter is not None:
            # ITL signal for the tick budgeter: same burst accounting the
            # step metrics use, with the reap's ready stamp as "now" so
            # the inter-reap gap is measured between readbacks.
            self._budgeter.observe_decode(
                self._t_last_ready - rec.t_dispatch, rec.occupancy,
                self.generated_tokens - gen0, now=self._t_last_ready,
            )
        self.flight.record(
            "reap", occupancy=rec.occupancy,
            tokens=self.generated_tokens - gen0,
            dur_ms=round(1000 * (self._t_last_ready - rec.t_dispatch), 3),
        )
        # Perf ledger: the same burst accounting, decomposed per shape
        # (width bucket, program variant, fused/fallback path) with the
        # dispatch/reap host split the stamps above already paid for. The
        # sentinel comparison itself is time-gated inside evaluate().
        self._perf.observe_decode(
            rec.nb_bucket,
            rec.variant,
            "fused" if rec.handles.mk_key is not None else "fallback",
            self._t_last_ready - rec.t_dispatch,
            self.generated_tokens - gen0,
            rec.occupancy,
            rec.avg_ctx,
            rec.gap_s,
            rec.dispatch_s,
            time.monotonic() - self._t_last_ready,
            now=self._t_last_ready,
        )
        self._perf.evaluate(now=self._t_last_ready)
        self._publish_stats()

    async def _drain_inflight(self) -> None:
        """Barrier: reap every in-flight burst. Required before any event
        that must see (or mutate) fully-reconciled slot/pool state —
        admission installs, speculative ticks, sleep, preemption."""
        while self._inflight:
            await self._reap_burst()

    def _abort_inflight(self) -> None:
        """Failure path: drop un-reaped bursts and resync EVERYTHING from
        the host mirrors. The device carry (pos/tokens) advanced past what
        was emitted; marking all slots dirty rolls the device state back to
        the scheduler's view, and the position-keyed sampling RNG makes the
        retried bursts regenerate the identical tokens."""
        aborted = len(self._inflight)
        self.flight.record("abort", inflight=aborted)
        # Post-mortem: persist both event rings (tick loop + device thread)
        # before the retry path overwrites the history that led here.
        # Rate-limited: a flapping device fails ticks repeatedly, and one
        # bounded dump per window captures the episode — an unbounded
        # stream of files (each a blocking write on this loop) would not.
        now = time.monotonic()
        path = None
        if now - self._last_flight_dump >= 30.0 and (
            self.flight.total or self.runner.flight.total
        ):
            path = dump_flight(
                {"engine": self.flight, "runner": self.runner.flight},
                reason="abort_inflight",
            )
            if path:
                # Stamp only on SUCCESS: a transiently unwritable dump dir
                # must not consume the rate-limit window for the episode.
                self._last_flight_dump = now
        logger.error(
            "aborted %d in-flight burst(s)%s", aborted,
            f"; flight recorder dumped to {path}" if path else "",
        )
        self._inflight.clear()
        self._dirty_state.update(range(self.args.max_num_seqs))
        self._dirty_tables.update(range(self.args.max_num_seqs))
        # Aborted proc-variant bursts already installed their advanced
        # out_counts into runner.proc_state at dispatch — rebuild every
        # live penalty-using slot's counts from the EMITTED history, or
        # the retry would apply penalties against double-counted tallies
        # (different logits → different tokens than the no-failure run).
        for slot, seq in enumerate(self._slots):
            if seq is not None and self._uses_procs[slot]:
                self.runner.proc_reset_slot(
                    slot, seq.request.token_ids, seq.generated
                )
        # Don't let the failure + retry-backoff window masquerade as host
        # gap on the next dispatch.
        self._t_last_ready = None
        self._publish_stats()

    def _emit_burst(
        self, seq: _Sequence, toks: np.ndarray, logps: np.ndarray,
        topv: Optional[np.ndarray] = None, topi: Optional[np.ndarray] = None,
    ) -> None:
        """Consume one fused burst for a sequence: apply stop conditions and
        stream ONE BackendOutput for the whole burst. Vectorized: the
        per-token Python loop cost ~0.2 s of pure host time per 64×256
        wave (16k iterations), which showed up directly as decode gap on
        the tunneled chip."""
        slot = seq.slot
        req = seq.request
        stop = req.stop
        K = len(toks)
        base = len(seq.generated)
        arr = np.asarray(toks)

        # Earliest stop position within the burst, per condition (K = none).
        def first_hit(token_ids, honor_min) -> int:
            if not token_ids:
                return K
            m = np.isin(arr, token_ids)
            if honor_min and stop.min_tokens is not None:
                # token k is the (base+k+1)-th generated token
                m &= (base + np.arange(K) + 1) >= stop.min_tokens
            idx = np.flatnonzero(m)
            return int(idx[0]) if idx.size else K

        eos_k = (
            K if stop.ignore_eos
            else first_hit(req.eos_token_ids or [], True)
        )
        stop_k = first_hit(stop.stop_token_ids or [], True)
        len_k = K
        if stop.max_tokens is not None:
            len_k = min(max(stop.max_tokens - base - 1, 0), K)
        model_k = min(
            max(self.args.max_model_len - len(seq.all_tokens) - 1, 0), K
        )
        cut = min(eos_k, stop_k, len_k, model_k)
        reason: Optional[FinishReason] = None
        if cut < K:
            # Precedence at the same position mirrors the per-token order:
            # EOS > STOP > LENGTH.
            if cut == eos_k:
                reason = FinishReason.EOS
            elif cut == stop_k:
                reason = FinishReason.STOP
            else:
                reason = FinishReason.LENGTH
        n_take = cut + 1 if cut < K else K
        emitted = arr[:n_take].tolist()
        emitted_logps = np.asarray(logps)[:n_take]
        seq.generated.extend(emitted)
        seq.all_tokens.extend(emitted)
        seq.next_token = emitted[-1]
        self._tok_mirror[slot] = emitted[-1]
        self.generated_tokens += n_take
        self._pos[slot] += n_take  # these tokens' KV is now resident
        self._commit_complete_blocks(seq, slot)

        logprobs = None
        if req.sampling.logprobs is not None:
            # Entry 0 is the SAMPLED token; entries 1.. are the request's
            # top-N alternatives (may repeat the sampled token, as OpenAI's
            # top_logprobs does when it ranks in the top N).
            n_top = min(int(req.sampling.logprobs), self.args.top_logprobs_cap)
            logprobs = []
            for k, (t, lp) in enumerate(zip(emitted, emitted_logps)):
                entry = [TokenLogprob(token_id=t, logprob=float(lp))]
                if topv is not None and n_top > 0:
                    entry.extend(
                        TokenLogprob(token_id=int(topi[k, j]), logprob=float(topv[k, j]))
                        for j in range(n_top)
                    )
                logprobs.append(entry)
        seq.queue.put_nowait(
            BackendOutput(
                token_ids=emitted,
                finish_reason=reason,
                cumulative_tokens=len(seq.generated),
                logprobs=logprobs,
            )
        )
        if reason is not None:
            self._finish(seq, reason, emit=False)

    def _commit_complete_blocks(self, seq: _Sequence, slot: int) -> None:
        """Commit every newly completed block (bulk form of the old
        per-token boundary check)."""
        args = self.args
        if not args.enable_prefix_caching:
            return
        pos = int(self._pos[slot])
        while True:
            bi = len(seq.block_hashes)
            if (bi + 1) * args.block_size > pos or bi >= len(seq.block_ids):
                return
            parent = seq.block_hashes[-1] if seq.block_hashes else None
            h = compute_block_hashes(
                seq.all_tokens[bi * args.block_size : (bi + 1) * args.block_size],
                args.block_size,
                parent_hash=parent,
                salt=seq.hash_salt,
            )[0]
            self.pool.commit(seq.block_ids[bi], h, parent)
            seq.block_hashes.append(h)
            if self.kvbm is not None:
                self.kvbm.notify_commit(h, bi + 1, parent=parent)

    def _preempt(self, seq: _Sequence) -> None:
        """Release blocks and requeue for recompute (vLLM-style preemption).
        Only ever reached with an empty pipeline (_dispatch_burst drains
        before letting allocation fail), so the recompute — whose sampling
        keys are position-salted — regenerates the identical stream."""
        # dynlint: disable=DYN002 -- preemption is a capacity event, not a steady-state tick: it fires at most once per pool exhaustion and operators page on it
        logger.warning("preempting request %s (KV pool exhausted)", seq.request.request_id)
        self.flight.record(
            "preempt", request_id=seq.request.request_id, slot=seq.slot,
            blocks=len(seq.block_ids),
        )
        self.pool.release(seq.block_ids, seq.block_hashes)
        slot = seq.slot
        self._slots[slot] = None
        self._pos[slot] = 0
        self._tok_mirror[slot] = 0
        self._dirty_state.add(slot)
        self.preemptions += 1
        seq.slot = -1
        self._requeue(seq)

    def _emit_token(
        self, seq: _Sequence, token: int, logprob: float,
        top: Optional[list] = None,  # [(token_id, logprob)] top-N candidates
    ) -> None:
        """Append a generated token, evaluate stop conditions, stream output."""
        seq.generated.append(token)
        seq.all_tokens.append(token)
        seq.next_token = token
        self.generated_tokens += 1
        req = seq.request
        stop = req.stop
        n = len(seq.generated)
        min_ok = stop.min_tokens is None or n >= stop.min_tokens
        reason: Optional[FinishReason] = None
        if not stop.ignore_eos and min_ok and token in (req.eos_token_ids or []):
            reason = FinishReason.EOS
        elif min_ok and token in (stop.stop_token_ids or []):
            reason = FinishReason.STOP
        elif stop.max_tokens is not None and n >= stop.max_tokens:
            reason = FinishReason.LENGTH
        elif len(seq.all_tokens) >= self.args.max_model_len:
            reason = FinishReason.LENGTH

        logprobs = None
        if req.sampling.logprobs is not None:
            entry = [TokenLogprob(token_id=token, logprob=logprob)]
            if top:
                n_top = min(int(req.sampling.logprobs), self.args.top_logprobs_cap)
                entry.extend(
                    TokenLogprob(token_id=t, logprob=lp) for t, lp in top[:n_top]
                )
            logprobs = [entry]
        seq.queue.put_nowait(
            BackendOutput(
                token_ids=[token],
                finish_reason=reason,
                cumulative_tokens=n,
                logprobs=logprobs,
            )
        )
        if reason is not None:
            self._finish(seq, reason, emit=False)

    # -- KV block export/import (disaggregation + tiered offload) ----------
    #
    # Threading contract: BlockPool (and its KV-event callback, which touches
    # asyncio state) is only ever mutated on the event-loop thread; ONLY the
    # device array work runs on the executor thread, which also serializes it
    # with decode steps (the caches are donated through every step).

    async def export_blocks_wire_async(self, block_hashes: List[int]):
        """Copy committed blocks out of HBM in POOL-NATIVE wire form
        (disagg/wire.py KvWireBlocks): quantized pools ship {q8, scales}
        without ever materializing the dense form — half the readback and
        half the wire; dense pools ship their storage dtype. Returns
        (found_hashes, wire) — the prefill side of disaggregated P/D
        (ref: kv_router/prefill_router.rs bootstrap → NIXL read; here the
        transfer is host-staged DCN, SURVEY §2.5 TPU-equivalent note).
        Stops at the first miss: only a leading run of the chain is useful.
        Found blocks are pinned across the device copy so eviction can't
        recycle them mid-gather."""
        matched, pinned_ids = self.pool.pin_prefix(block_hashes)
        try:
            ids = pinned_ids
            found = list(block_hashes[:matched])
            if not ids:
                return [], None

            # Two-phase: enqueue on the device thread (cheap), read back on
            # the transfer thread — decode ticks interleave with the copy.
            handles = await self._device(
                self.runner.gather_blocks_wire_dispatch, ids
            )
            wire = await asyncio.get_running_loop().run_in_executor(
                self._transfer_executor,
                self.runner.gather_blocks_wire_readback, handles,
            )
            # ``bytes`` is the ACTUAL serialized wire size (payload +
            # scales), not a post-dequant figure — the flight ring and the
            # bench read this as the transfer-plane cost.
            self.flight.record(
                "kv_export", blocks=len(found), bytes=int(wire.nbytes),
                dtype=wire.dtype,
            )
            return found, wire
        finally:
            if pinned_ids:
                self.pool.release(pinned_ids, block_hashes[: len(pinned_ids)])

    async def export_blocks_async(self, block_hashes: List[int]):
        """Dense-form export: (found_hashes, k_blocks, v_blocks) shaped
        [n, L, block_size, KH, D]. Kept for consumers that want dense
        arrays regardless of the pool encoding (checkpoint interop, the
        v1 transfer schema); quantized pools dequantize host-side to the
        v1 wire dtype. The transfer path proper should use
        export_blocks_wire_async."""
        found, wire = await self.export_blocks_wire_async(block_hashes)
        if wire is None:
            return found, None, None
        k, v = wire.to_dense()
        return found, k, v

    async def import_blocks_wire_async(
        self, block_hashes: List[int], wire,
        *, anchor_parent: Optional[int] = None,
    ) -> int:
        """Insert transferred wire blocks (KvWireBlocks) into the pool as
        cached (committed) content, so normal prefix-cached admission
        reuses them. Returns how many were installed (stops when the pool
        is dry). All four interop cells land here: int8 wire installs
        verbatim into int8 pools and dequantizes on device into dense
        pools; dense wire requantizes on device into int8 pools.

        ``anchor_parent``: hash the FIRST block chains from when the caller
        knows the preceding block (mid-tree restore, suffix transfer whose
        parent is already resident)."""
        ids: List[int] = []
        sel: List[int] = []
        parents: List[Optional[int]] = []
        parent: Optional[int] = anchor_parent
        for i, h in enumerate(block_hashes):
            if self.pool.contains(h):
                parent = h
                continue
            b = self.pool.alloc()
            if b is None:
                break
            # Allocated but NOT committed yet: private to us, so nobody can
            # pin the hash and attend over unwritten data.
            ids.append(b)
            sel.append(i)
            parents.append(parent)
            parent = h
        if not ids:
            return 0

        sub = wire.take(sel)
        try:
            await self._device(self.runner.scatter_blocks_wire, ids, sub)
        except Exception:
            for b in ids:
                self.pool.release([b], [])  # data never landed; just free
            raise
        for b, i, par in zip(ids, sel, parents):
            h = block_hashes[i]
            self.pool.commit(b, h, par)
            # imported blocks start unreferenced (cached): release our pin
            self.pool.release([b], [h])
        self.flight.record(
            "kv_import", blocks=len(ids), bytes=int(sub.nbytes),
            dtype=sub.dtype,
        )
        return len(ids)

    async def import_blocks_async(
        self, block_hashes: List[int], k_blocks, v_blocks,
        *, anchor_parent: Optional[int] = None,
    ) -> int:
        """Dense-form import (v1 surface): wraps the arrays as a dense
        wire payload and funnels through import_blocks_wire_async so the
        pin/scatter/commit/rollback invariants live in ONE place."""
        from dynamo_tpu.disagg.wire import KvWireBlocks

        return await self.import_blocks_wire_async(
            block_hashes,
            KvWireBlocks.dense(np.asarray(k_blocks), np.asarray(v_blocks)),
            anchor_parent=anchor_parent,
        )

    # -- live-handoff drain (runtime/drain.py DrainController) -------------
    #
    # Threading contract: every method here runs on the event-loop thread.
    # Slot/pool mutation happens ONLY inside _service_drain_queues, which
    # the scheduler loop calls behind its drain barrier — so detach and
    # adoption observe the same fully-reconciled state admission does, and
    # the position-keyed sampling RNG makes the continuation bit-identical.

    @property
    def draining(self) -> bool:
        return self._draining

    def begin_drain(self) -> None:
        """Stop admitting new work: generate() refuses with a typed
        migratable error, the admission loop holds, and load reports carry
        ``draining`` so the router deflects placement immediately."""
        if not self._draining:
            self._draining = True
            # A budget-parked prefill returns to the queue whole, so the
            # controller's shed pass sees it immediately (it runs on this
            # same loop thread; the park state only exists between ticks).
            self._unpark_pending()
            self.flight.record("drain_begin")
            self._publish_stats()
            self._wake.set()

    def end_drain(self) -> None:
        """Abort a drain and return to serving (operator rollback)."""
        if self._draining:
            self._draining = False
            self.flight.record("drain_end")
            self._publish_stats()
            self._wake.set()

    def active_request_ids(self) -> List[str]:
        return [
            s.request.request_id for s in self._slots if s is not None
        ]

    def has_waiting(self) -> bool:
        return bool(self._waiting)

    def shed_waiting_for_drain(self, exc_factory) -> int:
        """Fail every not-yet-admitted request with a typed migratable
        error (``exc_factory(request_id) -> BaseException``) — the drain
        ladder's "typed requeue" rung: nothing was computed, so the
        frontend re-dispatches the request whole to a serving worker."""
        n = 0
        while self._waiting:
            seq = self._waiting.popleft()
            self.flight.record(
                "drain_requeue", request_id=seq.request.request_id
            )
            seq.queue.put_nowait(exc_factory(seq.request.request_id))
            n += 1
        if n:
            self._publish_stats()
        return n

    async def detach_for_handoff(self, request_id: str) -> Optional[_Sequence]:
        """Pull a live sequence out of its slot at the next reconciled
        burst boundary. Returns None when the stream already finished.
        The detached sequence keeps its pool blocks (and its output queue —
        the client is still attached to it); decode for it stops until a
        peer adopts it or the caller fails it down the ladder."""
        await self.start()
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._detach_requests.append((request_id, fut))
        self._wake.set()
        return await fut

    def _service_drain_queues(self) -> None:
        """Scheduler-loop half of detach/adopt (behind the drain barrier)."""
        while self._detach_requests:
            rid, fut = self._detach_requests.popleft()
            if fut.done():
                continue
            seq = next(
                (
                    s for s in self._slots
                    if s is not None and s.request.request_id == rid
                ),
                None,
            )
            if seq is None:
                fut.set_result(None)  # finished while the request queued
                continue
            slot = seq.slot
            seq.detach_pos = int(self._pos[slot])
            seq.t_detached = time.monotonic()
            self._slots[slot] = None
            self._pos[slot] = 0
            self._tok_mirror[slot] = 0
            self._dirty_state.add(slot)
            seq.slot = -1
            self.flight.record(
                "handoff_detach", request_id=rid, pos=seq.detach_pos,
                blocks=len(seq.block_ids),
            )
            fut.set_result(seq)
        while self._adoptions:
            slot = self._free_slot()
            if slot is None:
                break  # at capacity; retry once a finish frees a slot
            self._install_adopted(self._adoptions.popleft(), slot)
        self._publish_stats()

    async def export_detached(self, seq: _Sequence):
        """Gather a detached sequence's resident KV in pool-native wire
        form. Returns (HandoffTicket, KvWireBlocks): every committed block
        plus the partial tail rows covering ``detach_pos`` — the peer
        resumes with ZERO re-prefilled tokens."""
        from dynamo_tpu.disagg.handoff import HandoffTicket

        args = self.args
        pos = seq.detach_pos
        n_blocks = -(-pos // args.block_size)  # ceil; pos >= 1 always
        ids = seq.block_ids[:n_blocks]
        committed = seq.block_hashes[: min(len(seq.block_hashes), n_blocks)]
        # Chaos seam: the draining worker failing to read its own pool —
        # the ladder must absorb this as a re-prefill fallback.
        fault_point(fault_names.DRAIN_HANDOFF_EXPORT)
        handles = await self._device(
            self.runner.gather_blocks_wire_dispatch, ids
        )
        wire = await asyncio.get_running_loop().run_in_executor(
            self._transfer_executor,
            self.runner.gather_blocks_wire_readback, handles,
        )
        self.handoffs_exported += 1
        self.flight.record(
            "handoff_export", request_id=seq.request.request_id,
            blocks=len(ids), bytes=int(wire.nbytes), dtype=wire.dtype,
        )
        cfg = self.config
        ticket = HandoffTicket(
            request=seq.request.to_dict(),
            generated=list(seq.generated),
            salt=seq.salt,
            hash_salt=seq.hash_salt,
            pos=pos,
            committed_hashes=list(committed),
            n_blocks=n_blocks,
            model=cfg.name,
            block_size=args.block_size,
            n_layers=cfg.n_layers,
            n_kv_heads=cfg.n_kv_heads,
            head_dim=cfg.head_dim_,
            seed=args.seed,
        )
        return ticket, wire

    def release_detached(self, seq: _Sequence) -> None:
        """Free a detached sequence's pool blocks (after the peer accepted
        the handoff, or before failing it down the ladder)."""
        self.pool.release(seq.block_ids, seq.block_hashes)
        seq.block_ids = []
        seq.block_hashes = []

    def fail_detached(self, seq: _Sequence, exc: BaseException) -> None:
        """Surface ``exc`` through the sequence's output stream (the
        serving handler raises it; a migratable type makes the frontend
        re-dispatch with the already-streamed tokens carried — the PR 7
        re-prefill rung of the drain ladder)."""
        if seq.block_ids:
            self.release_detached(seq)
        seq.queue.put_nowait(exc)

    async def adopt_handoff(self, ticket, wire, context: Context) -> _Sequence:
        """Peer side: install a HandoffTicket's blocks and queue the
        sequence for slot installation at the scheduler's next reconciled
        boundary. Raises HandoffRefused when this engine cannot take it
        (capacity, pool pressure, draining itself)."""
        from dynamo_tpu.disagg.handoff import HandoffRefused

        await self.start()
        if self._draining:
            raise HandoffRefused("peer is itself draining")
        if self._failure is not None:
            raise HandoffRefused(f"peer engine failed: {self._failure}")
        live = sum(1 for s in self._slots if s is not None)
        earmarked = len(self._adoptions) + self._admitting
        if self._pending_prefill is not None:
            # A budget-parked prefill batch holds slots-to-be exactly
            # like an in-flight admission does.
            earmarked += len(self._pending_prefill.batch)
        if live + earmarked >= self.args.max_num_seqs:
            raise HandoffRefused(
                f"no free slot ({live} live + {len(self._adoptions)} "
                f"pending adoptions + {self._admitting} admitting of "
                f"{self.args.max_num_seqs})"
            )
        # Chaos seam: the receiving worker dying mid-adoption — the source
        # absorbs it by trying the next peer or falling down the ladder.
        fault_point(fault_names.DRAIN_HANDOFF_IMPORT)
        committed = list(ticket.committed_hashes)
        n_committed = len(committed)
        if n_committed:
            # Shared-cache rows install through the proven disagg path
            # (pin/scatter/commit/rollback in ONE place), then pin for the
            # adopted sequence exactly like prefix-cached admission.
            await self.import_blocks_wire_async(
                committed, wire.take(list(range(n_committed)))
            )
        matched, ids = (
            self.pool.pin_prefix(committed) if committed else (0, [])
        )
        tail_ids: List[int] = []
        try:
            if matched < n_committed:
                raise HandoffRefused(
                    f"pool pressure: only {matched}/{n_committed} committed "
                    "blocks resident after import"
                )
            tail_rows = list(range(n_committed, ticket.n_blocks))
            for _ in tail_rows:
                b = self.pool.alloc()
                if b is None:
                    raise HandoffRefused("pool dry for private tail blocks")
                tail_ids.append(b)
            if tail_ids:
                await self._device(
                    self.runner.scatter_blocks_wire, tail_ids,
                    wire.take(tail_rows),
                )
        except Exception:
            self.pool.release(ids + tail_ids, committed[:matched])
            raise
        req = PreprocessedRequest.from_dict(dict(ticket.request))
        prompt = list(req.token_ids)
        seq = _Sequence(
            request=req,
            context=context,
            queue=asyncio.Queue(),
            prompt=prompt,
            all_tokens=prompt + list(ticket.generated),
            generated=list(ticket.generated),
            # RNG continuity: the ORIGINAL arrival salt, not a fresh one —
            # fold_in(seed, salt, pos) then draws the identical noise the
            # source would have drawn for every remaining token.
            salt=int(ticket.salt),
            hash_salt=int(ticket.hash_salt),
            detach_pos=int(ticket.pos),
        )
        seq.block_ids = ids + tail_ids
        seq.block_hashes = committed[:matched]
        self._adoptions.append(seq)
        self._wake.set()
        self.handoffs_adopted += 1
        self.flight.record(
            "handoff_adopt", request_id=req.request_id, pos=seq.detach_pos,
            blocks=len(seq.block_ids), carried=len(seq.generated),
        )
        return seq

    def _set_slot_state(
        self, seq: _Sequence, slot: int, *, pos: int, block_ids: Any,
        sp: Tuple[float, int, float], adapter_id: int, procs: Any,
        tok_mirror: int,
    ) -> None:
        """Every per-slot field the device-resident decode state reads,
        set for a new occupant. ONE implementation shared by
        Admitter._install (fresh admission) and _install_adopted (live
        handoff) — the two MUST stay field-for-field identical, or an
        adopted sequence samples with stale state from the slot's
        previous occupant and the bit-identical-continuation guarantee
        breaks.

        Mutates every field the device-resident decode state reads —
        reconcile at the next dispatch (_dirty_state/_dirty_tables).
        Installs only ever happen behind the scheduler's drain barrier,
        so no in-flight burst can be holding this slot stale-active.
        """
        seq.slot = slot
        self._slots[slot] = seq
        self._pos[slot] = pos
        self._block_tables[slot, :] = 0
        self._block_tables[slot, : len(block_ids)] = block_ids
        self._temp[slot], self._topk[slot], self._topp[slot] = sp
        self._adapter_ids[slot] = adapter_id
        self._salts[slot] = seq.salt
        self._tok_mirror[slot] = int(tok_mirror)
        self._dirty_state.add(slot)
        self._dirty_tables.add(slot)
        # Logits-processor slot state: neutral unless this occupant asks —
        # stale device bookkeeping from a previous occupant is harmless
        # under neutral params (identity transform).
        self._uses_procs[slot] = procs is not None
        if procs is None:
            self._minp[slot] = 0.0
            self._rep[slot] = 1.0
            self._pres[slot] = 0.0
            self._freq[slot] = 0.0
            self._bias_ids[slot, :] = -1
            self._bias_vals[slot, :] = 0.0
        else:
            self._minp[slot] = procs.minp
            self._rep[slot] = procs.rep
            self._pres[slot] = procs.pres
            self._freq[slot] = procs.freq
            self._bias_ids[slot] = procs.bias_ids
            self._bias_vals[slot] = procs.bias_vals
            # Exact penalty state: original prompt only in the mask;
            # generated tokens restore the output counts (re-admitted
            # preemption and adopted handoff both carry them).
            self.runner.proc_reset_slot(
                slot, seq.request.token_ids, seq.generated
            )

    def _install_adopted(self, seq: _Sequence, slot: int) -> None:
        """Slot installation for an adopted sequence — Admitter._install
        minus prefill and minus the first-token emit (everything up to the
        handoff point already reached the client through the source)."""
        req = seq.request
        self._set_slot_state(
            seq, slot, pos=seq.detach_pos, block_ids=seq.block_ids,
            sp=self._sampling_of(req),
            adapter_id=self._lora_index.get(req.lora_name or "", 0),
            procs=self._procs_of(req),
            # seq.generated already holds the handoff token: the source
            # counted it at emit, proc_reset_slot restores that count.
            tok_mirror=seq.all_tokens[-1],
        )
        seq.next_token = seq.all_tokens[-1]
        self.flight.record(
            "handoff_install", request_id=req.request_id, slot=slot,
            pos=seq.detach_pos,
        )

    async def stream_adopted(
        self, seq: _Sequence
    ) -> AsyncIterator[BackendOutput]:
        """Continuation outputs of an adopted sequence (handoff handler).
        The adopted portion gets its own engine.decode span (the peer's
        share of the trajectory; the source's decode span ended at
        detach)."""
        t0 = time.monotonic()
        try:
            async for out in self._stream_outputs(seq):
                yield out
        finally:
            if seq.context.baggage.get("traceparent"):
                try:
                    from dynamo_tpu.utils.tracing import export_span

                    export_span(
                        "engine.decode", seq.context, start_mono=t0,
                        proc=getattr(self, "trace_proc", None),
                        adopted=True, generated=len(seq.generated),
                    )
                except Exception:
                    logger.debug(
                        "adopted phase-span export failed", exc_info=True
                    )


    # -- checkpoint / restore (the chrek/CRIU fast-cold-start role) --------
    # Logic lives in engines/tpu/kv_checkpoint.py; these stay as the
    # engine's public surface (system server + worker shutdown use them).

    def record_ckpt_corruption(self, detail: str) -> None:
        """Flight-ring note for a CRC-failed checkpoint restore (called by
        kv_checkpoint.py; the append lives here so the engine stays the
        ring's single writer)."""
        self.flight.record("ckpt_corrupt", detail=detail)

    async def save_checkpoint(self, ckpt_dir: str) -> Dict[str, Any]:
        from dynamo_tpu.engines.tpu import kv_checkpoint

        return await kv_checkpoint.save_checkpoint(self, ckpt_dir)

    async def load_checkpoint(self, ckpt_dir: str) -> int:
        from dynamo_tpu.engines.tpu import kv_checkpoint

        return await kv_checkpoint.load_checkpoint(self, ckpt_dir)

    def _finish(self, seq: _Sequence, reason: FinishReason, emit: bool = True) -> None:
        self.flight.record(
            "finish", request_id=seq.request.request_id, reason=reason.value,
            generated=len(seq.generated),
        )
        self.pool.release(seq.block_ids, seq.block_hashes)
        seq.block_ids = []
        seq.block_hashes = []
        if seq.slot >= 0:
            self._slots[seq.slot] = None
            self._pos[seq.slot] = 0
            self._tok_mirror[seq.slot] = 0
            # Deactivate the device-side slot at the next dispatch: any
            # still-in-flight burst that has this row stale-active gets its
            # tokens dropped at reap, and the row stops advancing after.
            self._dirty_state.add(seq.slot)
            seq.slot = -1
        if emit:
            seq.queue.put_nowait(BackendOutput(finish_reason=reason))
