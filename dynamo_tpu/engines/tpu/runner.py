"""DeviceRunner: sole owner of the engine's device state and programs.

Split out of the engine monolith so the scheduler (engines/tpu/engine.py)
owns *policy* — admission, slots, stop conditions — while this owns
*mechanism*: params, LoRA stacks, KV cache arrays, RNG, the compiled step /
fused-decode / speculative-verify programs, sleep/wake device transitions,
and block gather/scatter. The reference keeps the same boundary between its
scheduler components and engine runtimes (SURVEY §2.2 native-engine role).

Multi-host SPMD: when constructed with a multi-process topology
(parallel/multihost.py), the runner on the leader mirrors every device
invocation over the op channel (runtime/network/spmd_channel.py) and the
runner on each follower replays it (engines/tpu/spmd.follow) — every
process issues identical global-mesh programs, the JAX-native form of the
reference's DP leader / non-leader ranks
(components/src/dynamo/vllm/main.py:67-78).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from dynamo_tpu.models import llama
from dynamo_tpu.ops.sampling import compute_logprobs, fold_row_keys, sample_tokens
from dynamo_tpu.parallel.sharding import ShardingRules, shard_params
from dynamo_tpu.runtime.device_observe import (
    FlightRecorder,
    global_compile_watcher,
    watched_jit,
)
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _next_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def _scatter_state_rows_impl(state, idx, rows):
    """Write ``rows[k][i]`` into ``state[k][idx[i]]`` for every slot-state
    field — ONE device program per row-count bucket, so a dirty-slot sync
    costs a single small H2D + dispatch regardless of how many per-slot
    arrays the decode state carries.

    Deliberately NOT donated: donating these dict-of-small-array operands
    through a shared module-level jit trips a native double-free in
    jaxlib 0.4.37's CPU client when the persistent compilation cache
    serves the executable (segfault at the next engine's buffer GC,
    reproduced under tests/). The copies are a few KB on rare mutating
    events — not a hot path."""
    return {k: state[k].at[idx].set(rows[k]) for k in state}


_scatter_state_rows = watched_jit(
    "runner.scatter_state_rows", jax.jit(_scatter_state_rows_impl)
)


def _scatter_table_rows_impl(tables, idx, rows):
    return tables.at[idx].set(rows)


_scatter_table_rows = watched_jit(
    "runner.scatter_table_rows", jax.jit(_scatter_table_rows_impl)
)


@dataclass
class _DecodeHandles:
    """Un-materialized device results of one dispatched decode burst.
    Returned by decode_dispatch; decode_read blocks on them. mk_key is the
    megakernel (width, logprobs, procs) provenness key, or None when the
    burst ran on the XLA path."""

    toks: Any
    logp: Any
    topv: Optional[Any] = None
    topi: Optional[Any] = None
    mk_key: Optional[Tuple[int, bool, bool]] = None


def _scatter_blocks_impl(cache, idx, blocks):
    """cache ← blocks [L, n, BS, KH, D] at idx [n]. Works on all layouts:
    stacked [L, NB, BS, KH, D], per-layer tuple of [NB, BS, KH, D], or
    per-layer int8 {"q8", "s"} pools (blocks arrive in the dequantized
    wire format and are re-quantized here — so bf16 and int8 engines
    interoperate over disagg/checkpoint transfers)."""
    from dynamo_tpu.ops.kv_quant import quantize_kv_chunk

    def one(c, blk):
        if isinstance(c, dict):
            q8, s = quantize_kv_chunk(blk)  # [n, BS, KH, D], [n, BS, KH]
            return {
                "q8": c["q8"].at[idx].set(q8),
                "s": c["s"].at[idx].set(s.transpose(0, 2, 1)),
            }
        return c.at[idx].set(blk.astype(c.dtype))

    if isinstance(cache, (tuple, list)):
        return tuple(one(c, blocks[l]) for l, c in enumerate(cache))
    return cache.at[:, idx].set(blocks.astype(cache.dtype))


_scatter_blocks = watched_jit(
    "runner.scatter_blocks",
    functools.partial(jax.jit, donate_argnums=(0,))(_scatter_blocks_impl),
)


# The DENSE wire/checkpoint dtype: int8 pools are dequantized to this by
# _gather_blocks when a dense export is requested (v1 importers, the
# checkpoint path); non-quantized pools ship in their storage dtype
# (casting would perturb fp32 test configs). The TRANSFER path prefers the
# pool-native wire form (gather_blocks_wire_* below + disagg/wire.py
# schema v2) — quantized pools then ship {q8, scales} without ever
# materializing the dense form. Chunk sizing on the transfer path must use
# disagg/wire.py::wire_block_bytes(), not a dtype literal.
KV_QUANT_WIRE_DTYPE = jnp.bfloat16


def _gather_blocks_impl(cache, idx):
    """[L, n, BS, KH, D] of blocks idx [n], from any cache layout, as ONE
    device program (a per-layer host gather would pay L dispatch RTTs).
    Int8 pools are dequantized to KV_QUANT_WIRE_DTYPE — the wire/checkpoint
    format is always dense [L, n, BS, KH, D]."""
    from dynamo_tpu.ops.kv_quant import dequantize_pages

    def one(c):
        if isinstance(c, dict):
            return dequantize_pages(
                c["q8"][idx], c["s"][idx], KV_QUANT_WIRE_DTYPE
            )
        return c[idx]

    if isinstance(cache, (tuple, list)):
        return jnp.stack([one(c) for c in cache])
    return cache[:, idx]


_gather_blocks = watched_jit("runner.gather_blocks", jax.jit(_gather_blocks_impl))


def _gather_blocks_q8_impl(cache, idx):
    """Pool-native gather of a QUANTIZED cache: (q8 [L, n, BS, KH, D] int8,
    s [L, n, KH, BS] f32) of blocks idx, with NO dequantization — half the
    HBM readback and half the wire of the dense form. One device program
    (same dispatch-RTT argument as _gather_blocks)."""
    q8 = jnp.stack([c["q8"][idx] for c in cache])
    s = jnp.stack([c["s"][idx] for c in cache])
    return q8, s


_gather_blocks_q8 = watched_jit(
    "runner.gather_blocks_q8", jax.jit(_gather_blocks_q8_impl)
)


def _scatter_blocks_q8_impl(cache, idx, q8, s):
    """cache ← quantized wire blocks (q8 [L, n, BS, KH, D], s [L, n, KH, BS])
    at idx. Quantized pools take them VERBATIM (an int8→int8 transfer is
    bit-exact); dense pools dequantize on device — either way the int8
    payload rides H2D at half the dense width."""
    from dynamo_tpu.ops.kv_quant import dequantize_pages

    def one(c, q8_l, s_l):
        if isinstance(c, dict):
            return {"q8": c["q8"].at[idx].set(q8_l), "s": c["s"].at[idx].set(s_l)}
        return c.at[idx].set(dequantize_pages(q8_l, s_l, c.dtype))

    if isinstance(cache, (tuple, list)):
        return tuple(one(c, q8[l], s[l]) for l, c in enumerate(cache))
    return cache.at[:, idx].set(dequantize_pages(q8, s, cache.dtype))


_scatter_blocks_q8 = watched_jit(
    "runner.scatter_blocks_q8",
    functools.partial(jax.jit, donate_argnums=(0,))(_scatter_blocks_q8_impl),
)


def _is_kernel_compile_error(exc: BaseException) -> bool:
    """Is this exception a kernel COMPILE/LOWERING failure (Mosaic
    rejection, VMEM/window limits, XLA compile errors) rather than a
    transient device/runtime error? The megakernel's fallback demotes only
    on these: a deterministic lowering failure will recur on every
    dispatch, while a transient error (device halt, tunnel hiccup,
    preempted RPC) would wrongly demote the engine to the ~1/3-roofline
    XLA decode path for the rest of its life."""
    msg = str(exc)
    low = msg.lower()
    if "mosaic" in low or "vmem" in low or "lowering" in low:
        return True
    names = {t.__name__ for t in type(exc).__mro__}
    if names & {
        "LoweringError",  # pallas/mosaic lowering rejections
        "MosaicError",
        "VerificationError",
    }:
        return True
    if "NotImplementedError" in names:
        # Mosaic "unsupported op" rejections — but only when the message
        # looks like one: an unrelated host-side NotImplementedError
        # (feature guard, library stub) must not demote the kernel.
        return (
            "unsupported" in low or "primitive" in low or "pallas" in low
        )
    if "XlaRuntimeError" in names:
        # jaxlib's catch-all execution error. Compile rejections carry
        # INTERNAL / UNIMPLEMENTED / RESOURCE_EXHAUSTED statuses; the
        # transport/device transients below must PROPAGATE, not demote.
        transient = (
            "UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED", "CANCELLED",
        )
        return not any(t in msg for t in transient)
    return False


def _adapter_to_host(adapter):
    """Keep retained adapters as host numpy: only the STACKED arrays belong
    in HBM — retaining per-adapter device copies for restacking would
    double LoRA device memory."""
    adapter.weights = {
        t: (np.asarray(A), np.asarray(B)) for t, (A, B) in adapter.weights.items()
    }
    return adapter


class DeviceRunner:
    """Device-state owner + program cache for one (possibly multi-process)
    logical worker. All ``run_*``/device methods are synchronous and meant
    to execute on the engine's single device thread (or the follower's main
    thread)."""

    def __init__(
        self,
        args: Any,  # JaxEngineArgs
        params: Optional[Any] = None,
        *,
        mesh=None,
        rules: Optional[ShardingRules] = None,
        topology=None,  # parallel/multihost.HostTopology
    ) -> None:
        self.args = args
        self.config = args.config
        self.mesh = mesh
        self.rules = rules or ShardingRules()
        self.topology = topology
        self.multihost = bool(topology is not None and topology.is_multihost)
        if getattr(args, "kv_cache_dtype", None) == "auto":
            # Measured policy (docs/design_docs/performance.md): int8 KV
            # loses at short context (+2 scale DMAs/page dominate) and wins
            # on long context + pool capacity. Quantize when the model
            # length crosses the break-even OR the pool cannot hold the
            # worst case at bf16 (capacity pressure -> halving bytes beats
            # preemption-by-recompute thrash).
            from dynamo_tpu import config as _cfg

            pool_tokens = args.num_kv_blocks * args.block_size
            pressure = pool_tokens < args.max_num_seqs * args.max_model_len
            args.kv_cache_dtype = (
                "int8"
                if args.layered_cache
                and (
                    args.max_model_len >= _cfg.KV_QUANT_AUTO_CTX.get()
                    or pressure
                )
                else None
            )
            logger.info(
                "kv_cache_dtype=auto resolved to %s (max_model_len=%d, "
                "pool_tokens=%d, pressure=%s)",
                args.kv_cache_dtype, args.max_model_len, pool_tokens,
                pressure,
            )
        self._spmd_tx = None  # SpmdBroadcaster on the leader
        backend = jax.default_backend()
        self.use_kernel = (
            args.use_kernel if args.use_kernel is not None else backend == "tpu"
        )
        from dynamo_tpu.ops.pallas.fused_layer import (
            supports_reason as _mk_supports_reason,
        )

        arch_reason = _mk_supports_reason(
            args.config, lora=bool(args.lora_dir), quantized_weights=True
        )
        mk_eligible = (
            args.layered_cache
            and not getattr(args, "kv_cache_dtype", None)
            and args.quantization == "int8"
            and mesh is None
            and args.max_num_seqs % 4 == 0
            and arch_reason is None
        )
        if args.use_megakernel is None:
            self.use_megakernel = backend == "tpu" and mk_eligible
        else:
            self.use_megakernel = bool(args.use_megakernel) and mk_eligible
            if args.use_megakernel and not mk_eligible:
                logger.warning(
                    "use_megakernel=True requested but the configuration is "
                    "ineligible (needs: layered bf16 cache, int8 weights, "
                    "no mesh/LoRA, max_num_seqs %% 4 == 0, supported "
                    "architecture%s) — falling back to the XLA decode path",
                    f"; architecture: {arch_reason}" if arch_reason else "",
                )
        if self.multihost and mesh is None:
            raise ValueError("multihost topology requires a device mesh")
        self._repl = (
            NamedSharding(mesh, P()) if (self.multihost and mesh is not None) else None
        )

        self._param_axes = llama.param_logical_axes(self.config)
        if args.quantization and args.quantization != "int8":
            raise ValueError(
                f"unsupported quantization {args.quantization!r} (int8 only)"
            )
        if params is None:
            if args.quantization:
                # Random-init directly in int8 — a full-precision tree
                # would fill HBM (8B fp ≈ a whole 16 GB chip) and fp init
                # on the single host core takes minutes at 8B scale.
                from dynamo_tpu.models.quantize import init_quantized_params

                params = init_quantized_params(self.config, args.seed)
            else:
                params = llama.init_params(
                    self.config, jax.random.PRNGKey(args.seed)
                )
        if args.quantization:
            from dynamo_tpu.models.quantize import quantize_params

            # Idempotent for pre-quantized checkpoints (hf_loader/weight
            # cache quantize host-side); rebuilds the axes tree either way.
            params, self._param_axes = quantize_params(params, self._param_axes)
        if mesh is not None:
            params = shard_params(params, self._param_axes, self.rules, mesh)
        if self.args.layered_cache and not isinstance(
            params.get("layers"), (tuple, list)
        ):
            # Serving layout: per-layer weight buffers next to the per-layer
            # KV pools (see llama.unstack_layer_params — removes the
            # per-step weight relayout fusions the stacked form costs).
            params = dict(
                params,
                layers=llama.unstack_layer_params(
                    params["layers"], self.config.n_layers
                ),
            )
            self._param_axes = dict(
                self._param_axes,
                layers=llama.unstack_layer_axes(
                    self._param_axes["layers"], self.config.n_layers
                ),
            )
        self.params = params
        self.k_cache, self.v_cache = self.alloc_kv_cache()

        # Multi-LoRA state: adapter name → index into the stacked arrays
        # (index 0 is the zero "no adapter" slot).
        self.lora: Optional[Dict[str, Any]] = None
        self.lora_index: Dict[str, int] = {}
        self._adapter_list: List[Optional[Any]] = []  # slot i ↔ stacked index i+1
        if args.lora_dir:
            self._load_loras(args.lora_dir)

        # RNG: ONE fixed base key. Decode/prefill sampling keys are derived
        # on device from (base key, sequence salt, token index) —
        # ops/sampling.fold_row_keys — so noise never depends on dispatch
        # order (the pipelined scheduler's determinism contract). The
        # host-side rng_step counter remains only for the speculative
        # verify program, which has no per-token position structure.
        self.rng = jax.random.PRNGKey(args.seed ^ 0x5EED)
        if self._repl is not None:
            self.rng = jax.device_put(self.rng, self._repl)
        self.rng_step = 0

        # Device-resident decode slot state: everything the fused decode
        # program reads per slot lives in HBM and is updated INCREMENTALLY
        # on the rare mutating events (admission, finish, preempt, block
        # append) via sync_slots/sync_tables — never re-uploaded from host
        # numpy on steady-state ticks. The engine keeps numpy mirrors as
        # the scheduler's view only. tokens/pos are additionally threaded
        # through each burst as a donated carry (decode_dispatch).
        from dynamo_tpu.ops.logits_process import MAX_BIAS_SLOTS

        S = args.max_num_seqs
        state0 = {
            "tokens": np.zeros(S, np.int32),
            "pos": np.zeros(S, np.int32),
            "active": np.zeros(S, np.int32),
            "temp": np.ones(S, np.float32),
            "topk": np.zeros(S, np.int32),
            "topp": np.ones(S, np.float32),
            "adapter_ids": np.zeros(S, np.int32),
            "salts": np.zeros(S, np.int32),
            "minp": np.zeros(S, np.float32),
            "rep": np.ones(S, np.float32),
            "pres": np.zeros(S, np.float32),
            "freq": np.zeros(S, np.float32),
            "bias_ids": np.full((S, MAX_BIAS_SLOTS), -1, np.int32),
            "bias_vals": np.zeros((S, MAX_BIAS_SLOTS), np.float32),
        }
        self.slot_state = {
            k: self._dev_persistent(v) for k, v in state0.items()
        }
        self.slot_tables = self._dev_persistent(
            np.zeros((S, args.max_blocks_per_seq), np.int32)
        )
        # H2D accounting for the hot path: every slot-state upload and
        # decode dispatch appends ("slot_sync"|"table_sync", rows) /
        # ("decode", nb). Tests assert steady-state ticks are pure
        # dispatches (no re-upload of pos/temp/topk/topp/adapter_ids/
        # block_tables); bounded ring so serving never grows it unbounded.
        self.transfer_log: List[Tuple[str, int]] = []
        self._transfer_log_cap = 4096
        # Device-thread flight ring: transfer syncs, decode dispatches, and
        # megakernel arm/prove/demote transitions. Separate ring from the
        # engine's (single-writer contract — this one is written from the
        # device-executor thread); /debug/flight merges them by timestamp.
        self.flight = FlightRecorder("runner")

        # Expected distinct-signature budget for the width-bucketed decode
        # and spec-verify programs: pow2 table widths give ~log2(cap)+1
        # buckets per program object; 2× + margin tolerates legitimate
        # re-specialization (LoRA stack restacks change operand shapes on
        # the same jit object). Crossing it means dispatch widths stopped
        # bucketing — the recompile-storm signal.
        width_buckets = max(int(args.max_blocks_per_seq), 1).bit_length() + 1
        self._decode_sig_budget = 2 * width_buckets + 4
        watcher = global_compile_watcher()
        for prog in ("runner.decode_state", "runner.spec_verify"):
            watcher.set_budget(prog, self._decode_sig_budget)

        # State-path decode programs, keyed (want_logprobs, use_procs,
        # use_megakernel). The logprob-free variant skips a full-vocab
        # log-softmax per fused step (the common case); processor variants
        # compile lazily on the first request that uses one; the XLA
        # (use_megakernel=False) variants back per-key demotions.
        self._decode_state_fns: Dict[Tuple[bool, bool, bool], Any] = {}
        self._step_fn = self._build_step_fn()
        # (want_procs, want_top, first_chunk) → lazily compiled prefill
        # program variants. first_chunk (fresh prefill, start_pos all 0)
        # uses dense in-chunk attention — zero paged reads.
        self._step_fns: Dict[Tuple[bool, bool, bool], Any] = {
            (False, False, False): self._step_fn
        }
        self.proc_state: Optional[Any] = None  # logits_process.ProcState
        # (table width, want_logprobs, uses_procs) combinations at which a
        # megakernel decode has succeeded. Each pow2 width bucket AND each
        # program variant compiles separately (a wider SMEM table — or the
        # first logprobs/processor request — can newly trip a lowering
        # limit long after the base program is serving fine), so the
        # compile-failure fallback stays armed per combination: a
        # compile-shaped error at an UNPROVEN one demotes; any error at a
        # proven one propagates (it cannot be a compile rejection — that
        # exact program already compiled and ran). Demotion is PER KEY
        # (r11): only the failing (width bucket, variant) routes to the
        # XLA decode program — every other bucket/variant (and the base
        # kernel) stays proven and keeps serving fused, so one pathological
        # long-context bucket can no longer demote the whole engine off
        # the roofline path. Demotions are logged loudly + flight-recorded.
        self._mk_proven_keys: set = set()
        self._mk_demoted_keys: set = set()  # per-(width, variant) demotions
        self._mk_armed_logged: set = set()  # flight "mk_arm" once per key
        # Decode-burst path accounting (megakernel coverage observability):
        # how many decode bursts dispatched on the fused path vs the XLA
        # fallback, total and per variant — surfaced through engine
        # stats()/metrics so a silent demotion can never masquerade as a
        # plain perf regression. Written on the device-executor thread,
        # read by stats snapshots (plain int/dict reads).
        self.mk_fused_bursts = 0
        self.mk_fallback_bursts = 0
        self.mk_bursts_by_variant: Dict[str, int] = {}
        self._spec_fn: Optional[Any] = None  # speculative verify program
        self.sleep_level = 0
        self.host_params: Optional[Any] = None

    # -- SPMD --------------------------------------------------------------

    def set_broadcaster(self, broadcaster) -> None:
        """Leader only: mirror every device op to the followers."""
        self._spmd_tx = broadcaster

    def _mirror(self, op: str, **kwargs: Any) -> None:
        if self._spmd_tx is not None:
            self._spmd_tx.send(op, **kwargs)

    def _dev_persistent(self, x):
        """Place a PERSISTENT array on device (LoRA stacks, anything that
        lives across dispatches). Unlike _dev, never returns host numpy —
        a persistent host array passed into every jit call would re-pay
        its full H2D transfer per dispatch."""
        if x is None:
            return None
        if self._repl is not None:
            return jax.device_put(np.ascontiguousarray(x), self._repl)
        return jnp.asarray(np.ascontiguousarray(x))

    def _dev(self, x):
        """Host → device conversion for replicated jit inputs. Multihost:
        every process supplies the identical full array, device_put builds
        the replicated global array. Single-process: hand numpy straight to
        jit — it folds the transfer into the dispatch instead of paying a
        separate device_put round-trip per argument (measured win on the
        tunneled platform where each sync transfer costs the full RTT)."""
        if x is None:
            return None
        if self._repl is not None:
            return jax.device_put(np.asarray(x), self._repl)
        return x

    def _constrain_out(self, *arrays):
        """Force small sampled outputs fully-replicated under multihost so
        every process (and the leader's numpy readback) can see them."""
        if not self.multihost:
            return arrays if len(arrays) > 1 else arrays[0]
        out = tuple(
            jax.lax.with_sharding_constraint(a, self._repl) for a in arrays
        )
        return out if len(out) > 1 else out[0]

    # -- allocation --------------------------------------------------------

    def alloc_kv_cache(self):
        k_cache, v_cache = llama.init_kv_cache(
            self.config, self.args.num_kv_blocks, self.args.block_size,
            layered=self.args.layered_cache,
            kv_dtype=getattr(self.args, "kv_cache_dtype", None),
        )
        if self.mesh is not None:
            if self.args.layered_cache:
                cache_sharding = self.rules.sharding(
                    self.mesh, *llama.kv_cache_layered_axes()
                )
                # int8 pools are {"q8": [NB, BS, KH, D], "s": [NB, KH, BS]}
                # dicts — the scale's kv_heads axis shards with the values.
                s_sharding = self.rules.sharding(
                    self.mesh, "kv_blocks", "kv_heads", None
                )

                def place(pool):
                    if isinstance(pool, dict):
                        return {
                            "q8": jax.device_put(pool["q8"], cache_sharding),
                            "s": jax.device_put(pool["s"], s_sharding),
                        }
                    return jax.device_put(pool, cache_sharding)

                k_cache = tuple(place(k) for k in k_cache)
                v_cache = tuple(place(v) for v in v_cache)
            else:
                cache_sharding = self.rules.sharding(
                    self.mesh, *llama.kv_cache_logical_axes()
                )
                k_cache = jax.device_put(k_cache, cache_sharding)
                v_cache = jax.device_put(v_cache, cache_sharding)
        return k_cache, v_cache

    # -- LoRA --------------------------------------------------------------

    def _load_loras(self, lora_dir: str) -> None:
        """Load every adapter under ``lora_dir`` and stack them layer-major
        for the layer-loop forward (lora/loader.py)."""
        from dynamo_tpu.lora import LocalLoRASource, load_lora_adapter

        source = LocalLoRASource(lora_dir)
        names = source.list_adapters()
        if not names:
            logger.warning("lora_dir %s contains no adapters", lora_dir)
            return
        self._adapter_list = [
            _adapter_to_host(
                load_lora_adapter(source.fetch(n, lora_dir), self.config, name=n)
            )
            for n in names
        ]
        self._restack_loras()

    def _restack_loras(self) -> None:
        """Rebuild the stacked LoRA arrays from ``_adapter_list`` (None
        entries are freed slots that keep later indices stable — in-flight
        sequences hold adapter ids by position)."""
        from dynamo_tpu.lora.loader import LoRAAdapter, stack_adapters

        real = [a for a in self._adapter_list if a is not None]
        if not real:
            self.lora = None
            self.lora_index = {}
            return
        padded = [
            a if a is not None
            else LoRAAdapter(name=f"__free_{i}", rank=1, scaling=0.0)
            for i, a in enumerate(self._adapter_list)
        ]
        targets = sorted({t for a in real for t in a.targets})
        stacked = stack_adapters(padded, self.config, targets)
        # [N+1, L, ...] → layer-major [L, N+1, ...] for the layer loop.
        self.lora = {
            t: (
                self._dev_persistent(A.swapaxes(0, 1)),
                self._dev_persistent(B.swapaxes(0, 1)),
            )
            for t, (A, B) in stacked.items()
        }
        self.lora_index = {
            a.name: i
            for i, a in enumerate(self._adapter_list, start=1)
            if a is not None
        }
        logger.info(
            "LoRA stack: %d slot(s), adapters %s (targets: %s)",
            len(self._adapter_list), sorted(self.lora_index), targets,
        )

    def install_adapter(self, adapter) -> None:
        """Add one host-resident adapter into a free slot and restack.
        Mirrored by value (not path) so followers need no shared FS."""
        self._mirror(
            "lora_install",
            name=adapter.name, rank=adapter.rank, scaling=adapter.scaling,
            weights={t: [A, B] for t, (A, B) in adapter.weights.items()},
        )
        for i, slot in enumerate(self._adapter_list):
            if slot is None:
                self._adapter_list[i] = adapter
                break
        else:
            self._adapter_list.append(adapter)
        self._restack_loras()

    def remove_adapter(self, name: str) -> int:
        """Free an adapter slot by name; returns its (stable) index."""
        self._mirror("lora_remove", name=name)
        idx = self.lora_index[name]
        self._adapter_list[idx - 1] = None
        self._restack_loras()
        return idx

    # -- jitted programs ---------------------------------------------------

    def _build_step_fn(self, want_procs: bool = False, want_top: bool = False,
                       first_chunk: bool = False):
        cfg = self.config
        use_kernel = self.use_kernel
        num_top = self.args.top_logprobs_cap if want_top else 0

        def step(params, lora, k_cache, v_cache, tokens, start_pos, chunk_lens,
                 block_tables, salts, rng, temp, topk, topp, adapter_ids,
                 mm_embeds, mm_slot,
                 minp=None, rep=None, pres=None, freq=None,
                 bias_ids=None, bias_vals=None, pmask=None):
            logits, k_cache, v_cache = llama.forward_paged(
                params, cfg, tokens, start_pos, chunk_lens, block_tables,
                k_cache, v_cache, use_kernel=use_kernel,
                lora=lora, adapter_ids=adapter_ids,
                mm_embeds=mm_embeds, mm_slot=mm_slot,
                first_chunk=first_chunk,
            )
            # Sampling key per row = (base key, sequence salt, index of the
            # sampled token) — start_pos + chunk_lens is exactly the index
            # the sampled token will occupy, matching decode_multi's
            # per-step fold so a preempted sequence's recompute redraws
            # identical noise for the same position.
            row_keys = fold_row_keys(rng, salts, start_pos + chunk_lens)
            if want_procs:
                from dynamo_tpu.ops import logits_process as lp

                # At the first sampled token only the prompt has been seen.
                pp = lp.ProcParams(rep=rep, pres=pres, freq=freq,
                                   bias_ids=bias_ids, bias_vals=bias_vals)
                logits = lp.apply_prompt_only(logits, pmask, pp)
                toks = sample_tokens(logits, None, temp, topk, topp, minp,
                                     row_keys=row_keys)
            else:
                toks = sample_tokens(logits, None, temp, topk, topp,
                                     row_keys=row_keys)
            logp = compute_logprobs(logits, toks)
            if num_top > 0:
                from dynamo_tpu.ops.sampling import top_logprobs as top_op

                tv, ti = top_op(logits, num_top)
                toks, logp, tv, ti = self._constrain_out(toks, logp, tv, ti)
                return toks, logp, tv, ti, k_cache, v_cache
            toks, logp = self._constrain_out(toks, logp)
            return toks, logp, k_cache, v_cache

        return watched_jit(
            "runner.prefill_step", jax.jit(step, donate_argnums=(2, 3))
        )

    def _build_decode_fn(self, want_logprobs: bool = False,
                         want_procs: bool = False,
                         use_megakernel: Optional[bool] = None):
        """Fused-decode program over the DEVICE-RESIDENT slot state.

        Inputs beyond params/caches are the slot-state arrays (tokens, pos,
        active, table slice, salts, sampling/processor params) — all device
        arrays, so a steady-state dispatch moves zero host bytes. tokens
        and pos are donated and come back as the carry (last sampled token
        + advanced position per slot), which the runner installs as the
        next burst's inputs without any host round trip.

        Output layout: (toks [S,K], logps [S,K][, top_vals, top_ids],
        k_cache, v_cache[, proc_counts], carry_tokens [S], carry_pos [S]).
        """
        cfg = self.config
        use_kernel = self.use_kernel
        if use_megakernel is None:
            use_megakernel = self.use_megakernel
        num_steps = self.args.decode_steps

        # The logprobs program variants also surface the per-step top-N
        # alternatives (OpenAI top_logprobs); the common variants skip it.
        num_top = self.args.top_logprobs_cap if want_logprobs else 0

        if not want_procs:
            def step(params, lora, k_cache, v_cache, tokens, pos, active,
                     block_tables, salts, rng, temp, topk, topp, adapter_ids):
                out = llama.decode_multi(
                    params, cfg, tokens, pos, active, block_tables,
                    k_cache, v_cache, rng, temp, topk, topp,
                    num_steps=num_steps, use_kernel=use_kernel,
                    use_megakernel=use_megakernel,
                    lora=lora, adapter_ids=adapter_ids,
                    want_logprobs=want_logprobs,
                    num_top_logprobs=num_top,
                    salts=salts, want_carry=True,
                )
                # out = (*small, k, v, carry_tok, carry_pos)
                small = self._constrain_out(*out[:-4])
                if not isinstance(small, tuple):
                    small = (small,)
                carry = self._constrain_out(*out[-2:])
                return small + out[-4:-2] + carry

            return watched_jit(
                "runner.decode_state",
                jax.jit(step, donate_argnums=(2, 3, 4, 5)),
                budget=self._decode_sig_budget,
            )

        from dynamo_tpu.ops import logits_process as lp

        def step_p(params, lora, k_cache, v_cache, tokens, pos, active,
                   block_tables, salts, rng, temp, topk, topp, adapter_ids,
                   minp, rep, pres, freq, bias_ids, bias_vals, counts, pmask):
            pp = lp.ProcParams(rep=rep, pres=pres, freq=freq,
                               bias_ids=bias_ids, bias_vals=bias_vals)
            st = lp.ProcState(out_counts=counts, prompt_mask=pmask)
            out = llama.decode_multi(
                params, cfg, tokens, pos, active, block_tables,
                k_cache, v_cache, rng, temp, topk, topp,
                num_steps=num_steps, use_kernel=use_kernel,
                use_megakernel=use_megakernel,
                lora=lora, adapter_ids=adapter_ids,
                want_logprobs=want_logprobs,
                min_p=minp, proc_params=pp, proc_state=st,
                num_top_logprobs=num_top,
                salts=salts, want_carry=True,
            )
            # out = (*small, k, v, proc_state, carry_tok, carry_pos)
            st = out[-3]
            small = self._constrain_out(*out[:-5])
            if not isinstance(small, tuple):
                small = (small,)
            carry = self._constrain_out(*out[-2:])
            return small + (out[-5], out[-4], st.out_counts) + carry

        # donate caches + tokens/pos carry + the token-count array.
        return watched_jit(
            "runner.decode_state",
            jax.jit(step_p, donate_argnums=(2, 3, 4, 5, 20)),
            budget=self._decode_sig_budget,
        )

    def _build_spec_fn(self):
        cfg = self.config
        use_kernel = self.use_kernel

        def step(params, lora, k_cache, v_cache, tokens, start_pos, chunk_lens,
                 block_tables, adapter_ids, rng, rng_step, temp, topk, topp):
            from dynamo_tpu.ops.sampling import spec_verify_sample

            rng = jax.random.fold_in(rng, rng_step)
            logits, k_cache, v_cache = llama.forward_paged(
                params, cfg, tokens, start_pos, chunk_lens, block_tables,
                k_cache, v_cache, use_kernel=use_kernel,
                lora=lora, adapter_ids=adapter_ids, all_logits=True,
            )
            # Rejection-sampling verify: exact target-distribution sampling
            # for temperature>0 rows, greedy verify for temperature<=0 rows
            # — ONE program serves mixed ticks (r4's greedy-only gate made
            # spec ~never engage on production traffic).
            emitted, counts = spec_verify_sample(
                logits, tokens[:, 1:], jnp.maximum(chunk_lens - 1, 0),
                rng, temp, topk, topp,
            )
            emitted, counts = self._constrain_out(emitted, counts)
            return emitted, counts, k_cache, v_cache

        return watched_jit(
            "runner.spec_verify",
            jax.jit(step, donate_argnums=(2, 3)),
            budget=self._decode_sig_budget,
        )

    # -- logits-processor device state ------------------------------------

    def ensure_proc_state(self):
        if self.proc_state is None:
            from dynamo_tpu.ops import logits_process as lp

            self.proc_state = lp.init_state(
                self.args.max_num_seqs, self.config.vocab_size
            )
        return self.proc_state

    def proc_reset_slot(self, slot: int, prompt_ids, generated) -> None:
        """(Re)initialize one slot's processor bookkeeping; mirrored so
        follower proc_state stays bit-identical."""
        from dynamo_tpu.ops import logits_process as lp

        self._mirror(
            "proc_reset", slot=slot,
            prompt_ids=np.asarray(prompt_ids, dtype=np.int32),
            generated=np.asarray(generated, dtype=np.int32),
        )
        st = self.ensure_proc_state()
        self.proc_state = lp.reset_slot(st, slot, list(prompt_ids), list(generated))

    def proc_count(self, slot: int, token: int) -> None:
        from dynamo_tpu.ops import logits_process as lp

        self._mirror("proc_count", slot=slot, token=int(token))
        st = self.ensure_proc_state()
        self.proc_state = lp.count_token(st, slot, int(token))

    # -- device invocations ------------------------------------------------

    @staticmethod
    def _get_all(*arrays):
        """Readback that pipelines the host transfers: start every copy
        async, then materialize. On the tunneled platform each synchronous
        device_get pays the full dispatch RTT (~77 ms); overlapping them
        collapses N round-trips into ~one."""
        for a in arrays:
            if a is not None and hasattr(a, "copy_to_host_async"):
                try:
                    a.copy_to_host_async()
                # dynlint: disable=DYN003 -- best-effort prefetch: device_get below is the real (reported) readback, and a per-array log here would spam every reap on backends without async copies
                except Exception:
                    pass
        return tuple(
            None if a is None else np.asarray(jax.device_get(a))
            for a in arrays
        )

    def run_step(
        self, tokens, start_pos, chunk_lens, block_tables, temp, topk, topp,
        adapter_ids, mm_embeds=None, mm_slot=None, procs=None, want_top=False,
        first_chunk=False, salts=None,
    ):
        """One prefill/verify forward + sample. Returns (tokens, logprobs,
        top_vals | None, top_ids | None) as numpy.

        ``procs``: optional (minp, rep, pres, freq, bias_ids, bias_vals,
        prompt_mask) per-row arrays — routes through the logits-processor
        program. ``want_top``: also return the top-N alternatives.
        ``first_chunk``: every row is a fresh prefill (start_pos == 0) —
        selects the dense in-chunk attention program (no paged reads).
        ``salts``: per-row sequence salts for the position-keyed sampling
        RNG. Defaults to arange(rows) so rows keep independent noise for
        direct callers (the engine always passes real sequence salts)."""
        if salts is None:
            salts = np.arange(len(np.asarray(tokens)), dtype=np.int32)
        self._mirror(
            "step", tokens=tokens, start_pos=start_pos, chunk_lens=chunk_lens,
            block_tables=block_tables, temp=temp, topk=topk, topp=topp,
            adapter_ids=adapter_ids, mm_embeds=mm_embeds, mm_slot=mm_slot,
            procs=None if procs is None else list(procs), want_top=want_top,
            first_chunk=first_chunk, salts=salts,
        )
        key = (procs is not None, bool(want_top), bool(first_chunk))
        fn = self._step_fns.get(key)
        if fn is None:
            fn = self._build_step_fn(
                want_procs=key[0], want_top=key[1], first_chunk=key[2]
            )
            self._step_fns[key] = fn
        d = self._dev
        args = [
            self.params, self.lora, self.k_cache, self.v_cache,
            d(tokens), d(start_pos), d(chunk_lens), d(block_tables),
            d(np.asarray(salts, dtype=np.int32)), self.rng,
            d(temp), d(topk), d(topp), d(adapter_ids),
            d(mm_embeds), d(mm_slot),
        ]
        if procs is not None:
            minp, rep, pres, freq, bias_ids, bias_vals, pmask = procs
            args += [
                d(minp), d(rep), d(pres), d(freq),
                d(bias_ids), d(bias_vals), d(pmask),
            ]
        out = fn(*args)
        topv = topi = None
        if want_top:
            toks, logp, topv, topi, self.k_cache, self.v_cache = out
        else:
            toks, logp, self.k_cache, self.v_cache = out
        return self._get_all(toks, logp, topv, topi)

    # -- device-resident decode slot state ---------------------------------

    def _log_transfer(self, kind: str, n: int) -> None:
        if len(self.transfer_log) >= self._transfer_log_cap:
            del self.transfer_log[: self._transfer_log_cap // 2]
        self.transfer_log.append((kind, n))
        # Same events, typed + timestamped, in the device-thread flight
        # ring (transfer_log stays as the tests' raw H2D count assertion).
        self.flight.record(kind, n=n)

    def sync_slots(self, slots, rows: Dict[str, Any]) -> None:
        """Scatter dirty slot rows into the device-resident decode state —
        the ONLY H2D path for pos/active/sampling/processor params after
        engine start. ``rows[k][i]`` lands at ``slot_state[k][slots[i]]``.
        Row counts are pow2-padded (repeating row 0 — idempotent) so the
        scatter compiles per bucket, not per count."""
        slots = [int(s) for s in slots]
        if not slots:
            return
        rows = {k: np.asarray(v) for k, v in rows.items()}
        if set(rows) != set(self.slot_state):
            raise ValueError(
                f"slot sync rows {sorted(rows)} != state fields "
                f"{sorted(self.slot_state)}"
            )
        self._mirror("slot_sync", slots=np.asarray(slots, np.int32),
                     rows=rows)
        R = _next_pow2(len(slots))
        idx = np.asarray(slots + [slots[0]] * (R - len(slots)), np.int32)
        padded = {
            k: np.concatenate([v, np.repeat(v[:1], R - len(slots), axis=0)])
            if R > len(slots) else v
            for k, v in rows.items()
        }
        d = self._dev
        self.slot_state = _scatter_state_rows(
            self.slot_state, d(idx), {k: d(v) for k, v in padded.items()}
        )
        self._log_transfer("slot_sync", len(slots))

    def sync_tables(self, slots, rows) -> None:
        """Scatter dirty block-table rows (full table width) into the
        device-resident table. Called only when a slot's table actually
        changed (admission, block append, preempt) — steady-state decode
        ticks never re-upload tables."""
        slots = [int(s) for s in slots]
        if not slots:
            return
        rows = np.asarray(rows, np.int32)
        self._mirror("table_sync", slots=np.asarray(slots, np.int32),
                     rows=rows)
        R = _next_pow2(len(slots))
        idx = np.asarray(slots + [slots[0]] * (R - len(slots)), np.int32)
        if R > len(slots):
            rows = np.concatenate(
                [rows, np.repeat(rows[:1], R - len(slots), axis=0)]
            )
        d = self._dev
        self.slot_tables = _scatter_table_rows(
            self.slot_tables, d(idx), d(rows)
        )
        self._log_transfer("table_sync", len(slots))

    def decode_dispatch(self, nb: int, want_logprobs: bool = False,
                        use_procs: bool = False) -> "_DecodeHandles":
        """ENQUEUE one fused decode burst over the device-resident slot
        state and return un-materialized result handles. No host arrays
        are read or written: the block table is sliced on device to the
        ``nb`` width bucket, tokens/pos come from the previous burst's
        donated carry, and the outputs start their D2H copies
        asynchronously. Pair with :meth:`decode_read` (leader) — followers
        dispatch and drop the handles.

        Megakernel compile-failure safety net: each (width bucket, program
        variant) compiles lazily at its first dispatch — if Mosaic rejects
        it on this jaxlib/chip, demote THAT key to the XLA decode path
        instead of poisoning serving. NARROW by design: only
        compile/lowering-shaped errors, only at combinations that have
        never succeeded (_mk_proven_keys, marked at first successful
        readback), and only the failing (width bucket, variant) key — all
        other buckets/variants stay proven and keep dispatching fused
        (_mk_demoted_keys)."""
        nb = int(nb)
        self._mirror(
            "decode_state", nb=nb, want_logprobs=bool(want_logprobs),
            use_procs=bool(use_procs),
        )
        key = (nb, bool(want_logprobs), bool(use_procs))
        if self.use_megakernel and key not in self._mk_demoted_keys:
            if key not in self._mk_proven_keys and key not in self._mk_armed_logged:
                # Fallback armed for a never-proven (width, variant): a
                # compile-shaped failure here demotes instead of raising.
                self._mk_armed_logged.add(key)
                self.flight.record(
                    "mk_arm", width=nb, logprobs=bool(want_logprobs),
                    procs=bool(use_procs),
                )
            try:
                return self._decode_dispatch_inner(
                    nb, want_logprobs, use_procs, use_mk=True, mk_key=key
                )
            except Exception as exc:
                if (
                    key in self._mk_proven_keys
                    or not _is_kernel_compile_error(exc)
                ):
                    raise
                logger.exception(
                    "megakernel decode failed to compile/lower at table "
                    "width %d (logprobs=%s, procs=%s) — demoting THIS "
                    "(width, variant) key to the XLA decode path; other "
                    "buckets/variants keep the fused path", *key,
                )
                self.flight.record(
                    "mk_demote", width=nb, logprobs=bool(want_logprobs),
                    procs=bool(use_procs), error=type(exc).__name__,
                )
                self._mk_demoted_keys.add(key)
        return self._decode_dispatch_inner(
            nb, want_logprobs, use_procs, use_mk=False
        )

    def _variant_label(self, nb, want_logprobs, use_procs) -> str:
        """Prometheus-safe per-variant key for the burst counters."""
        return (
            f"w{int(nb)}"
            + ("_logprobs" if want_logprobs else "")
            + ("_procs" if use_procs else "")
        )

    def _decode_dispatch_inner(self, nb, want_logprobs, use_procs,
                               use_mk=False, mk_key=None) -> "_DecodeHandles":
        variant = (bool(want_logprobs), bool(use_procs), bool(use_mk))
        fn = self._decode_state_fns.get(variant)
        if fn is None:
            fn = self._build_decode_fn(
                want_logprobs=variant[0], want_procs=variant[1],
                use_megakernel=variant[2],
            )
            self._decode_state_fns[variant] = fn
        st = self.slot_state
        tables_nb = self.slot_tables[:, :nb]
        topv = topi = None
        base = (
            self.params, self.lora, self.k_cache, self.v_cache,
            st["tokens"], st["pos"], st["active"], tables_nb, st["salts"],
            self.rng, st["temp"], st["topk"], st["topp"], st["adapter_ids"],
        )
        if use_procs:
            ps = self.ensure_proc_state()
            out = fn(
                *base, st["minp"], st["rep"], st["pres"], st["freq"],
                st["bias_ids"], st["bias_vals"],
                ps.out_counts, ps.prompt_mask,
            )
            from dynamo_tpu.ops import logits_process as lp

            if want_logprobs:
                (toks, logp, topv, topi, self.k_cache, self.v_cache,
                 counts, carry_tok, carry_pos) = out
            else:
                (toks, logp, self.k_cache, self.v_cache, counts,
                 carry_tok, carry_pos) = out
            self.proc_state = lp.ProcState(
                out_counts=counts, prompt_mask=ps.prompt_mask
            )
        else:
            out = fn(*base)
            if want_logprobs:
                (toks, logp, topv, topi, self.k_cache, self.v_cache,
                 carry_tok, carry_pos) = out
            else:
                (toks, logp, self.k_cache, self.v_cache,
                 carry_tok, carry_pos) = out
        # Install the carry as the next burst's input — tokens/pos never
        # travel through the host on the decode hot loop.
        self.slot_state = dict(
            self.slot_state, tokens=carry_tok, pos=carry_pos
        )
        self._log_transfer("decode", nb)
        # Coverage accounting: the dispatch succeeded on this path. The
        # per-variant split rides stats()/metrics so a demoted variant
        # shows up as fallback bursts, never as a silent perf regression.
        label = self._variant_label(nb, want_logprobs, use_procs)
        if use_mk:
            self.mk_fused_bursts += 1
            self.mk_bursts_by_variant[label] = (
                self.mk_bursts_by_variant.get(label, 0) + 1
            )
        else:
            self.mk_fallback_bursts += 1
        return _DecodeHandles(
            toks=toks, logp=logp, topv=topv, topi=topi, mk_key=mk_key
        )

    def decode_read(self, handles: "_DecodeHandles"):
        """Blocking readback half of decode_dispatch. Returns ([S, K]
        tokens, [S, K] logprobs, top_vals | None, top_ids | None) numpy."""
        out = self._get_all(
            handles.toks, handles.logp, handles.topv, handles.topi
        )
        if handles.mk_key is not None:
            # The megakernel program for this (width, variant) both
            # compiled AND executed — arm propagate-don't-demote for it.
            if handles.mk_key not in self._mk_proven_keys:
                self._mk_proven_keys.add(handles.mk_key)
                self.flight.record(
                    "mk_prove", width=handles.mk_key[0],
                    logprobs=handles.mk_key[1], procs=handles.mk_key[2],
                )
        return out

    def run_decode(
        self, tokens, start_pos, active, block_tables, temp, topk, topp,
        adapter_ids, want_logprobs=False, procs=None, salts=None,
    ):
        """Synchronous convenience form (tests, tools): seed the slot state
        from host arrays, dispatch one burst, read it back. The serving
        engine drives sync_slots/decode_dispatch/decode_read directly.
        ``procs``: optional (minp, rep, pres, freq, bias_ids, bias_vals)
        slot arrays → the processor program. Returns ([B, K] tokens,
        [B, K] logprobs, top_vals | None, top_ids | None) as numpy."""
        S = len(np.asarray(tokens))
        if procs is not None:
            minp, rep, pres, freq, bias_ids, bias_vals = procs
        else:
            from dynamo_tpu.ops.logits_process import MAX_BIAS_SLOTS

            minp = np.zeros(S, np.float32)
            rep = np.ones(S, np.float32)
            pres = np.zeros(S, np.float32)
            freq = np.zeros(S, np.float32)
            bias_ids = np.full((S, MAX_BIAS_SLOTS), -1, np.int32)
            bias_vals = np.zeros((S, MAX_BIAS_SLOTS), np.float32)
        self.sync_slots(
            list(range(S)),
            {
                "tokens": np.asarray(tokens, np.int32),
                "pos": np.asarray(start_pos, np.int32),
                "active": np.asarray(active, np.int32),
                "temp": np.asarray(temp, np.float32),
                "topk": np.asarray(topk, np.int32),
                "topp": np.asarray(topp, np.float32),
                "adapter_ids": np.asarray(adapter_ids, np.int32),
                # arange default keeps rows' noise independent for direct
                # callers (the engine supplies real sequence salts).
                "salts": (
                    np.arange(S, dtype=np.int32) if salts is None
                    else np.asarray(salts, np.int32)
                ),
                "minp": np.asarray(minp, np.float32),
                "rep": np.asarray(rep, np.float32),
                "pres": np.asarray(pres, np.float32),
                "freq": np.asarray(freq, np.float32),
                "bias_ids": np.asarray(bias_ids, np.int32),
                "bias_vals": np.asarray(bias_vals, np.float32),
            },
        )
        tables = np.asarray(block_tables, np.int32)
        nb = tables.shape[1]
        full = np.zeros((S, self.slot_tables.shape[1]), np.int32)
        full[:, : min(nb, full.shape[1])] = tables[:, : full.shape[1]]
        self.sync_tables(list(range(S)), full)
        handles = self.decode_dispatch(
            nb, want_logprobs=want_logprobs, use_procs=procs is not None
        )
        return self.decode_read(handles)

    def run_spec(self, tokens, start_pos, chunk_lens, block_tables,
                 adapter_ids, temp=None, topk=None, topp=None):
        """Speculative verify with rejection sampling: returns
        (emitted [S, C] tokens, counts [S]) — row i's first counts[i]
        entries are the accepted prefix + the corrected/bonus token."""
        S = tokens.shape[0]
        if temp is None:
            temp = np.zeros(S, dtype=np.float32)  # greedy
        if topk is None:
            topk = np.zeros(S, dtype=np.int32)
        if topp is None:
            topp = np.ones(S, dtype=np.float32)
        self._mirror(
            "spec", tokens=tokens, start_pos=start_pos, chunk_lens=chunk_lens,
            block_tables=block_tables, adapter_ids=adapter_ids,
            temp=temp, topk=topk, topp=topp,
        )
        if self._spec_fn is None:
            self._spec_fn = self._build_spec_fn()
        step_id = np.int32(self.rng_step & 0x7FFFFFFF)
        self.rng_step += 1
        d = self._dev
        emitted, counts, self.k_cache, self.v_cache = self._spec_fn(
            self.params, self.lora, self.k_cache, self.v_cache,
            d(tokens), d(start_pos), d(chunk_lens), d(block_tables),
            d(adapter_ids), self.rng, step_id, d(temp), d(topk), d(topp),
        )
        return (
            np.asarray(jax.device_get(emitted)),
            np.asarray(jax.device_get(counts)),
        )

    # -- block transfer (disagg / checkpoint) ------------------------------

    def pool_quantized(self) -> bool:
        """Is the KV pool stored quantized ({q8, s} per layer)?"""
        from dynamo_tpu.ops.kv_quant import is_quantized_pool

        kc = self.k_cache
        if isinstance(kc, (tuple, list)):
            kc = kc[0]
        return is_quantized_pool(kc)

    def kv_wire_dtype(self) -> str:
        """Pool-native wire dtype tag (disagg/wire.py schema): "int8" for
        quantized pools, the storage dtype name otherwise."""
        if self.pool_quantized():
            return "int8"
        return str(jnp.dtype(self.config.dtype).name)

    def gather_blocks_dispatch(self, ids: List[int]):
        """ENQUEUE the block gather and return the (not-yet-read) device
        arrays. Runs on the device-executor thread but only pays dispatch
        cost — the synchronous HBM→host readback happens in
        gather_blocks_readback on a transfer thread, so decode ticks keep
        flowing while a disagg/offload transfer drains (the overlap the
        reference gets from its async offload engine + stream-based copies,
        lib/llm/src/block_manager/offload.rs:1, block/transfer/cuda.rs:1).
        Device-side ordering is safe: the gather program is enqueued before
        any later decode step, so donated cache updates cannot outrun it."""
        self._mirror("gather", ids=np.asarray(ids, dtype=np.int32))
        idx = self._dev(np.asarray(ids, dtype=np.int32))
        k = _gather_blocks(self.k_cache, idx)
        v = _gather_blocks(self.v_cache, idx)
        if self.multihost:
            # Followers also compute the gather (they must join the
            # collective); only the leader reads it back, replicated.
            k, v = self._constrain_out(k, v)
        return k.swapaxes(0, 1), v.swapaxes(0, 1)

    @staticmethod
    def gather_blocks_readback(k, v) -> Tuple[np.ndarray, np.ndarray]:
        """Blocking readback half of gather_blocks_dispatch — call from a
        transfer executor, never the device thread."""
        return (
            np.asarray(jax.device_get(k)), np.asarray(jax.device_get(v))
        )

    def gather_blocks(self, ids: List[int]) -> Tuple[np.ndarray, np.ndarray]:
        """Copy blocks out of HBM → ([n, L, BS, KH, D] k, v) numpy.
        Synchronous convenience form (SPMD followers, tests)."""
        return self.gather_blocks_readback(*self.gather_blocks_dispatch(ids))

    def scatter_blocks(self, ids: List[int], k_blocks, v_blocks) -> None:
        """Insert [n, L, BS, KH, D] host blocks into HBM at ``ids``."""
        self._mirror(
            "scatter", ids=np.asarray(ids, dtype=np.int32),
            k_blocks=np.asarray(k_blocks), v_blocks=np.asarray(v_blocks),
        )
        idx = self._dev(np.asarray(ids, dtype=np.int32))
        k_sel = self._dev(
            np.asarray(k_blocks).swapaxes(0, 1).astype(self.config.dtype)
        )
        v_sel = self._dev(
            np.asarray(v_blocks).swapaxes(0, 1).astype(self.config.dtype)
        )
        self.k_cache = _scatter_blocks(self.k_cache, idx, k_sel)
        self.v_cache = _scatter_blocks(self.v_cache, idx, v_sel)

    # -- pool-native wire transfer (disagg/wire.py schema v2) --------------

    def gather_blocks_wire_dispatch(self, ids: List[int]):
        """ENQUEUE a pool-native gather and return un-read device handles.
        Quantized pools ship {q8, scales} WITHOUT dequantizing — half the
        readback and half the wire; dense pools reuse the dense dispatch.
        Same two-phase contract as gather_blocks_dispatch (readback on the
        transfer thread keeps decode ticks flowing)."""
        if not self.pool_quantized():
            k, v = self.gather_blocks_dispatch(ids)  # mirrors "gather"
            return ("dense", self.kv_wire_dtype(), k, v)
        self._mirror("gather_wire", ids=np.asarray(ids, dtype=np.int32))
        idx = self._dev(np.asarray(ids, dtype=np.int32))
        kq, ks = _gather_blocks_q8(self.k_cache, idx)
        vq, vs = _gather_blocks_q8(self.v_cache, idx)
        if self.multihost:
            kq, ks, vq, vs = self._constrain_out(kq, ks, vq, vs)
        return (
            "q8", "int8",
            kq.swapaxes(0, 1), ks.swapaxes(0, 1),
            vq.swapaxes(0, 1), vs.swapaxes(0, 1),
        )

    @staticmethod
    def gather_blocks_wire_readback(handles):
        """Blocking readback half of gather_blocks_wire_dispatch — call
        from a transfer executor, never the device thread. Returns
        disagg/wire.py KvWireBlocks."""
        from dynamo_tpu.disagg.wire import KvWireBlocks

        if handles[0] == "dense":
            _, dtype, k, v = handles
            return KvWireBlocks(
                dtype=dtype,
                k=np.asarray(jax.device_get(k)),
                v=np.asarray(jax.device_get(v)),
            )
        _, dtype, kq, ks, vq, vs = handles
        return KvWireBlocks(
            dtype=dtype,
            k=np.asarray(jax.device_get(kq)),
            v=np.asarray(jax.device_get(vq)),
            k_scale=np.asarray(jax.device_get(ks)),
            v_scale=np.asarray(jax.device_get(vs)),
        )

    def gather_blocks_wire(self, ids: List[int]):
        """Synchronous convenience form (SPMD followers, tests)."""
        return self.gather_blocks_wire_readback(
            self.gather_blocks_wire_dispatch(ids)
        )

    def scatter_blocks_wire(self, ids: List[int], wire) -> None:
        """Install wire blocks (KvWireBlocks) into HBM at ``ids``. Dense
        payloads reuse scatter_blocks (which requantizes into int8 pools on
        device); quantized payloads ship int8 over H2D and install verbatim
        (int8 pool) or dequantize on device (dense pool)."""
        if not wire.quantized:
            self.scatter_blocks(ids, wire.k, wire.v)
            return
        self._mirror(
            "scatter_wire", ids=np.asarray(ids, dtype=np.int32),
            k_q8=np.asarray(wire.k), k_s=np.asarray(wire.k_scale),
            v_q8=np.asarray(wire.v), v_s=np.asarray(wire.v_scale),
        )
        idx = self._dev(np.asarray(ids, dtype=np.int32))
        kq = self._dev(np.asarray(wire.k).swapaxes(0, 1))
        ks = self._dev(np.asarray(wire.k_scale).swapaxes(0, 1))
        vq = self._dev(np.asarray(wire.v).swapaxes(0, 1))
        vs = self._dev(np.asarray(wire.v_scale).swapaxes(0, 1))
        self.k_cache = _scatter_blocks_q8(self.k_cache, idx, kq, ks)
        self.v_cache = _scatter_blocks_q8(self.v_cache, idx, vq, vs)

    # -- sleep / wake device transitions -----------------------------------

    def sleep_device(self, level: int) -> None:
        """Free device memory. Level 1: KV cache; level 2: weights → host.
        Level 2 is single-host only (a tp-sharded global param tree is not
        addressable from one process)."""
        if level >= 2 and self.multihost:
            raise RuntimeError(
                "sleep level 2 (weight offload) is unsupported in multihost "
                "mode; use level 1"
            )
        self._mirror("sleep", level=level)
        self.k_cache = None
        self.v_cache = None
        if level >= 2:
            self.host_params = jax.device_get(self.params)
            self.params = None
        self.sleep_level = level
        logger.info("engine asleep at level %d", level)

    def wake_device(self) -> None:
        self._mirror("wake")
        if self.sleep_level >= 2 and self.host_params is not None:
            params = self.host_params
            self.host_params = None
            if self.mesh is not None:
                params = shard_params(
                    params, self._param_axes, self.rules, self.mesh
                )
            else:
                params = jax.tree_util.tree_map(jnp.asarray, params)
            self.params = params
        if self.k_cache is None:
            self.k_cache, self.v_cache = self.alloc_kv_cache()
        self.sleep_level = 0
        logger.info("engine awake")
