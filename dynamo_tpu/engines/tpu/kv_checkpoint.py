"""Warm-KV checkpoint/restore (the chrek/CRIU fast-cold-start role).

Reference parity: deploy/chrek/pkg/checkpoint/criu.go — the reference
snapshots whole containers; on TPU a process image can't capture HBM, so
the TPU-native equivalent persists the expensive-to-rebuild state
explicitly: weights via models/weight_cache.py (GMS tiers), the warmed KV
prefix cache via these functions. A restored worker serves shared-prefix
traffic without re-prefilling.

Split from the engine monolith: the engine exposes thin
save_checkpoint/load_checkpoint delegates; all manifest/order logic lives
here.
"""

from __future__ import annotations

import json
import os
import uuid
import zipfile
from typing import Any, Dict, List

import numpy as np

from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class _CorruptCheckpoint(Exception):
    """CRC mismatch in a checkpoint data file (internal control flow)."""


def read_manifest(ckpt_dir: str):
    try:
        with open(os.path.join(ckpt_dir, "manifest.json")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


async def save_checkpoint(engine: Any, ckpt_dir: str) -> Dict[str, Any]:
    """Persist the warm prefix cache: every committed KV block plus its
    hash-chain metadata."""
    os.makedirs(ckpt_dir, exist_ok=True)
    snap = engine.pool.snapshot_committed()
    hashes = [h for h, _, _ in snap]
    ids = [bid for _, _, bid in snap]
    try:
        # The manifest is the commit point: it names the (nonce-unique)
        # data file, so a crash at any point leaves the OLD manifest
        # pointing at the OLD data — never a mismatched pair (same
        # atomic-publish rule as models/weight_cache.py save_params).
        data_name = f"kv_blocks-{uuid.uuid4().hex[:12]}.npz" if ids else ""
        crc = {}
        if ids:
            def gather_and_write():
                from dynamo_tpu.kvbm.integrity import array_crc32

                k, v = engine.runner.gather_blocks(ids)
                # Per-array CRC32 stamped into the manifest: a restore
                # verifies before installing, so a corrupt/truncated data
                # file is a counted miss, never silently-garbage KV.
                crc["k"] = array_crc32(k)
                crc["v"] = array_crc32(v)
                # Disk write stays off the event loop (multi-GB stall).
                np.savez(os.path.join(ckpt_dir, data_name), k=k, v=v)

            await engine._device(gather_and_write)
        manifest = {
            "version": 1,
            "model": engine.config.name,
            "block_size": engine.args.block_size,
            "n_layers": engine.config.n_layers,
            "n_kv_heads": engine.config.n_kv_heads,
            "head_dim": engine.config.head_dim_,
            "data": data_name,
            "crc": crc,
            "blocks": [{"hash": h, "parent": p} for h, p, _ in snap],
        }
        tmp = os.path.join(ckpt_dir, f".manifest-{uuid.uuid4().hex[:8]}")
        with open(tmp, "w") as f:
            json.dump(manifest, f)
        old = read_manifest(ckpt_dir)
        os.replace(tmp, os.path.join(ckpt_dir, "manifest.json"))
        if old and old.get("data") and old["data"] != data_name:
            try:  # best-effort cleanup of the superseded data file
                os.unlink(os.path.join(ckpt_dir, old["data"]))
            except OSError:
                pass
        logger.info("checkpointed %d KV blocks to %s", len(ids), ckpt_dir)
        return {"blocks": len(ids), "path": ckpt_dir}
    finally:
        if ids:
            engine.pool.release(ids, hashes)


async def load_checkpoint(engine: Any, ckpt_dir: str) -> int:
    """Restore a save_checkpoint() capture into the pool as cached content.
    Returns the number of blocks installed (stops early when the pool is
    dry); raises ValueError on a shape/model mismatch."""
    with open(os.path.join(ckpt_dir, "manifest.json")) as f:
        manifest = json.load(f)
    for key, ours in (
        ("model", engine.config.name),
        ("block_size", engine.args.block_size),
        ("n_layers", engine.config.n_layers),
        ("n_kv_heads", engine.config.n_kv_heads),
        ("head_dim", engine.config.head_dim_),
    ):
        if manifest.get(key) != ours:
            raise ValueError(
                f"checkpoint {key}={manifest.get(key)!r} does not match "
                f"engine {key}={ours!r}"
            )
    blocks = manifest.get("blocks", [])
    if not blocks:
        return 0
    data_name = manifest.get("data") or "kv_blocks.npz"
    want_crc = manifest.get("crc") or {}

    def read():  # disk read off the event loop
        from dynamo_tpu.kvbm.integrity import array_crc32

        data = np.load(os.path.join(ckpt_dir, data_name))
        k, v = data["k"], data["v"]
        # Verify BEFORE anything lands in the pool. Manifests written
        # before the CRC stamp (no "crc" field) restore unverified.
        for name, arr in (("k", k), ("v", v)):
            want = want_crc.get(name)
            if want is None:
                continue
            got = array_crc32(arr)
            if got != int(want):
                raise _CorruptCheckpoint(
                    f"{data_name}:{name} CRC mismatch "
                    f"(manifest {want}, file {got})"
                )
        return k, v

    try:
        k_all, v_all = await engine._device(read)
    except (
        _CorruptCheckpoint, OSError, ValueError, KeyError,
        zipfile.BadZipFile,
    ) as exc:
        # Corrupt or truncated data file: a counted miss — the worker
        # starts cold instead of crashing (or worse, attending over
        # garbage KV). A truncated npz raises BadZipFile (a plain
        # Exception, NOT an OSError); OSError/ValueError cover the rest.
        from dynamo_tpu.kvbm.integrity import note_corruption

        note_corruption("checkpoint")
        note_fn = getattr(engine, "record_ckpt_corruption", None)
        if note_fn is not None:
            note_fn(f"{type(exc).__name__}: {exc}")
        logger.warning(
            "KV checkpoint %s failed integrity/read (%s); restoring "
            "nothing — next requests prefill cold", ckpt_dir, exc,
        )
        return 0
    index_of = {b["hash"]: i for i, b in enumerate(blocks)}

    # Parents-first install order (chains form a forest).
    placed = set()
    ordered: List[Dict[str, Any]] = []
    pending = list(blocks)
    while pending:
        progressed = False
        rest = []
        for b in pending:
            parent = b["parent"]
            if (
                parent is None
                or parent in placed
                or engine.pool.contains(parent)
            ):
                ordered.append(b)
                placed.add(b["hash"])
                progressed = True
            else:
                rest.append(b)
        pending = rest
        if not progressed:
            logger.warning(
                "checkpoint restore: %d blocks have unreachable parents",
                len(pending),
            )
            break

    # Split into parent-linked runs and reuse the proven disagg install
    # path (pin/scatter/commit/rollback invariants live in ONE place).
    installed = 0
    i = 0
    while i < len(ordered):
        j = i + 1
        while j < len(ordered) and ordered[j]["parent"] == ordered[j - 1]["hash"]:
            j += 1
        run = ordered[i:j]
        sel = [index_of[b["hash"]] for b in run]
        installed += await engine.import_blocks_async(
            [b["hash"] for b in run], k_all[sel], v_all[sel],
            anchor_parent=run[0]["parent"],
        )
        i = j
    logger.info("restored %d KV blocks from %s", installed, ckpt_dir)
    return installed
