"""Warm-KV checkpoint/restore (the chrek/CRIU fast-cold-start role).

Reference parity: deploy/chrek/pkg/checkpoint/criu.go — the reference
snapshots whole containers; on TPU a process image can't capture HBM, so
the TPU-native equivalent persists the expensive-to-rebuild state
explicitly: weights via models/weight_cache.py (GMS tiers), the warmed KV
prefix cache via these functions. A restored worker serves shared-prefix
traffic without re-prefilling.

Crash-plane contract (ISSUE 10): restore can NEVER be the reason a worker
fails to come up. Every failure mode resolves to a logged cold start with
a counted outcome (runtime/liveness.py ``restore_outcome_total``):

  * the manifest carries a **compatibility stamp** (model, block layout,
    engine sampling seed — the seed gates bit-identical continuation the
    same way handoff tickets do); a mismatched stamp skips the restore
    (``cold_mismatch``), it does not raise;
  * every block row carries its own CRC32, so partial corruption drops
    ONLY the bad blocks (and their now-unreachable children) — the rest
    restore (``partial``); a fully unreadable archive is ``cold_corrupt``;
  * anything else (including the ``restore.load`` chaos seam) is
    ``cold_error``.

Split from the engine monolith: the engine exposes thin
save_checkpoint/load_checkpoint delegates; all manifest/order logic lives
here.
"""

from __future__ import annotations

import json
import os
import time
import uuid
import zipfile
from typing import Any, Dict, List, Optional

import numpy as np

from dynamo_tpu.runtime import fault_names
from dynamo_tpu.runtime.faults import fault_point
from dynamo_tpu.runtime.liveness import note_restore
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class _CorruptCheckpoint(Exception):
    """Whole-archive integrity failure (internal control flow)."""


def read_manifest(ckpt_dir: str):
    try:
        with open(os.path.join(ckpt_dir, "manifest.json")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _stamp_of(engine: Any) -> Dict[str, Any]:
    """The compatibility stamp: restored KV is only valid on an engine
    with the same weights/layout, and only bit-identically continuable
    with the same sampling seed (the fold_in(seed, salt, pos) keys)."""
    return {
        "model": engine.config.name,
        "block_size": engine.args.block_size,
        "n_layers": engine.config.n_layers,
        "n_kv_heads": engine.config.n_kv_heads,
        "head_dim": engine.config.head_dim_,
        "seed": getattr(engine.args, "seed", 0),
    }


def stamp_mismatch(manifest: Dict[str, Any], engine: Any) -> Optional[str]:
    """First mismatching stamp field as ``"key: theirs != ours"``, or
    None when compatible. Manifests older than the seed stamp (no "seed"
    key) only check the fields they carry."""
    for key, ours in _stamp_of(engine).items():
        if key == "seed" and "seed" not in manifest:
            continue  # pre-stamp manifest: seedless, shape-checked only
        theirs = manifest.get(key)
        if theirs != ours:
            return f"{key}: checkpoint {theirs!r} != engine {ours!r}"
    return None


async def save_checkpoint(engine: Any, ckpt_dir: str) -> Dict[str, Any]:
    """Persist the warm prefix cache: every committed KV block plus its
    hash-chain metadata, CRC-stamped per block row."""
    os.makedirs(ckpt_dir, exist_ok=True)
    snap = engine.pool.snapshot_committed()
    hashes = [h for h, _, _ in snap]
    ids = [bid for _, _, bid in snap]
    try:
        # The manifest is the commit point: it names the (nonce-unique)
        # data file, so a crash at any point leaves the OLD manifest
        # pointing at the OLD data — never a mismatched pair (same
        # atomic-publish rule as models/weight_cache.py save_params).
        data_name = f"kv_blocks-{uuid.uuid4().hex[:12]}.npz" if ids else ""
        crc_k: List[int] = []
        crc_v: List[int] = []
        if ids:
            def gather_and_write():
                from dynamo_tpu.kvbm.integrity import array_crc32

                k, v = engine.runner.gather_blocks(ids)
                # Per-BLOCK CRC32 stamped into the manifest: restore
                # verifies row by row, so partial corruption drops only
                # the bad blocks instead of the whole warm cache.
                for i in range(len(ids)):
                    crc_k.append(array_crc32(k[i]))
                    crc_v.append(array_crc32(v[i]))
                # Disk write stays off the event loop (multi-GB stall).
                np.savez(os.path.join(ckpt_dir, data_name), k=k, v=v)

            await engine._device(gather_and_write)
        manifest = {
            "version": 2,
            **_stamp_of(engine),
            "data": data_name,
            "blocks": [
                {"hash": h, "parent": p, "crc_k": ck, "crc_v": cv}
                for (h, p, _), ck, cv in zip(snap, crc_k, crc_v)
            ],
        }
        tmp = os.path.join(ckpt_dir, f".manifest-{uuid.uuid4().hex[:8]}")
        with open(tmp, "w") as f:
            json.dump(manifest, f)
        old = read_manifest(ckpt_dir)
        os.replace(tmp, os.path.join(ckpt_dir, "manifest.json"))
        if old and old.get("data") and old["data"] != data_name:
            try:  # best-effort cleanup of the superseded data file
                os.unlink(os.path.join(ckpt_dir, old["data"]))
            except OSError:
                pass
        logger.info("checkpointed %d KV blocks to %s", len(ids), ckpt_dir)
        return {"blocks": len(ids), "path": ckpt_dir}
    finally:
        if ids:
            engine.pool.release(ids, hashes)


async def load_checkpoint(engine: Any, ckpt_dir: str) -> int:
    """Restore a save_checkpoint() capture into the pool as cached
    content. Returns the number of blocks installed. NEVER raises on a
    bad checkpoint: a mismatched stamp, a corrupt/truncated archive, an
    empty directory, or the restore machinery failing outright all
    resolve to a logged, metric-counted cold start (0 blocks) — a crash
    loop here would turn one bad file into an unserving fleet."""
    t0 = time.monotonic()
    try:
        return await _load_checkpoint(engine, ckpt_dir, t0)
    except Exception as exc:
        # The restore machinery itself failed (the restore.load chaos
        # seam injects exactly this): cold start, counted, never a crash.
        note_restore("cold_error", time.monotonic() - t0)
        logger.warning(
            "KV checkpoint restore from %s failed (%s: %s); starting cold",
            ckpt_dir, type(exc).__name__, exc,
        )
        return 0


async def _load_checkpoint(engine: Any, ckpt_dir: str, t0: float) -> int:
    # Chaos seam: one hit per restore attempt, before anything is read —
    # an injected error proves the cold_error path (counted cold start).
    fault_point(fault_names.RESTORE_LOAD, dir=ckpt_dir)
    manifest = read_manifest(ckpt_dir)
    if manifest is None:
        # Empty/fresh checkpoint dir: the normal first boot.
        note_restore("empty", time.monotonic() - t0)
        return 0
    mismatch = stamp_mismatch(manifest, engine)
    if mismatch is not None:
        # A different model/layout/seed wrote this checkpoint (image
        # upgrade, config change): its KV is not ours to install.
        note_restore("cold_mismatch", time.monotonic() - t0)
        logger.warning(
            "KV checkpoint %s stamp mismatch (%s); starting cold",
            ckpt_dir, mismatch,
        )
        return 0
    blocks = manifest.get("blocks", [])
    if not blocks:
        note_restore("empty", time.monotonic() - t0)
        return 0
    data_name = manifest.get("data") or "kv_blocks.npz"
    legacy_crc = manifest.get("crc") or {}

    corrupt_rows: List[int] = []

    def read():  # disk read off the event loop
        from dynamo_tpu.kvbm.integrity import array_crc32

        data = np.load(os.path.join(ckpt_dir, data_name))
        k, v = data["k"], data["v"]
        if len(k) != len(blocks) or len(v) != len(blocks):
            raise _CorruptCheckpoint(
                f"{data_name} holds {len(k)}/{len(v)} rows for "
                f"{len(blocks)} manifest blocks"
            )
        # Verify BEFORE anything lands in the pool. v2 manifests carry a
        # CRC per block row — only the bad rows (and their chain
        # descendants) are dropped; v1 manifests fall back to the
        # whole-array CRC (all-or-nothing); older ones restore unverified.
        for i, b in enumerate(blocks):
            want_k, want_v = b.get("crc_k"), b.get("crc_v")
            if want_k is None and want_v is None:
                continue
            if (want_k is not None and array_crc32(k[i]) != int(want_k)) or (
                want_v is not None and array_crc32(v[i]) != int(want_v)
            ):
                corrupt_rows.append(i)
        for name, arr in (("k", k), ("v", v)):
            want = legacy_crc.get(name)
            if want is not None and array_crc32(arr) != int(want):
                raise _CorruptCheckpoint(
                    f"{data_name}:{name} CRC mismatch (manifest {want})"
                )
        return k, v

    try:
        k_all, v_all = await engine._device(read)
    except (
        _CorruptCheckpoint, OSError, ValueError, KeyError,
        zipfile.BadZipFile,
    ) as exc:
        # Fully corrupt or truncated data file: a counted miss — the
        # worker starts cold instead of crashing (or worse, attending
        # over garbage KV). A truncated npz raises BadZipFile (a plain
        # Exception, NOT an OSError); OSError/ValueError cover the rest.
        from dynamo_tpu.kvbm.integrity import note_corruption

        note_corruption("checkpoint")
        note_restore("cold_corrupt", time.monotonic() - t0)
        note_fn = getattr(engine, "record_ckpt_corruption", None)
        if note_fn is not None:
            note_fn(f"{type(exc).__name__}: {exc}")
        logger.warning(
            "KV checkpoint %s failed integrity/read (%s); restoring "
            "nothing — next requests prefill cold", ckpt_dir, exc,
        )
        return 0
    if corrupt_rows:
        from dynamo_tpu.kvbm.integrity import note_corruption

        note_corruption("checkpoint", len(corrupt_rows))
        note_fn = getattr(engine, "record_ckpt_corruption", None)
        if note_fn is not None:
            note_fn(f"{len(corrupt_rows)} block rows failed CRC")
        logger.warning(
            "KV checkpoint %s: dropping %d/%d blocks with CRC mismatches "
            "(their chain descendants become unreachable and drop too)",
            ckpt_dir, len(corrupt_rows), len(blocks),
        )
        bad = set(corrupt_rows)
        blocks = [b for i, b in enumerate(blocks) if i not in bad]
    index_of = {b["hash"]: i for i, b in enumerate(manifest.get("blocks", []))}

    # Parents-first install order (chains form a forest). A block whose
    # parent was CRC-dropped never progresses and is pruned here — a
    # child must not commit under a parent that never installed.
    placed = set()
    ordered: List[Dict[str, Any]] = []
    pending = list(blocks)
    while pending:
        progressed = False
        rest = []
        for b in pending:
            parent = b["parent"]
            if (
                parent is None
                or parent in placed
                or engine.pool.contains(parent)
            ):
                ordered.append(b)
                placed.add(b["hash"])
                progressed = True
            else:
                rest.append(b)
        pending = rest
        if not progressed:
            logger.warning(
                "checkpoint restore: %d blocks have unreachable parents",
                len(pending),
            )
            break

    # Split into parent-linked runs and reuse the proven disagg install
    # path (pin/scatter/commit/rollback invariants live in ONE place).
    installed = 0
    i = 0
    while i < len(ordered):
        j = i + 1
        while j < len(ordered) and ordered[j]["parent"] == ordered[j - 1]["hash"]:
            j += 1
        run = ordered[i:j]
        sel = [index_of[b["hash"]] for b in run]
        installed += await engine.import_blocks_async(
            [b["hash"] for b in run], k_all[sel], v_all[sel],
            anchor_parent=run[0]["parent"],
        )
        i = j
    total = len(manifest.get("blocks", []))
    # "partial" means CORRUPTION dropped blocks — the signal operators
    # alert on. A clean checkpoint that installs fewer than the manifest
    # lists for capacity reasons (pool dry, resident blocks, a child
    # pruned under an absent-but-uncorrupt parent) is still "restored";
    # the installed/total counts are in the log line.
    note_restore(
        "partial" if corrupt_rows else "restored",
        time.monotonic() - t0,
    )
    logger.info(
        "restored %d/%d KV blocks from %s", installed, total, ckpt_dir
    )
    return installed
