"""SLA-driven intra-chip prefill/decode disaggregation: the tick budgeter.

Full disaggregation buys ITL isolation at the price of a KV transfer tax
— and the bench record shows naive one-chip timeshared disagg is a
measured 6× regression. Nexus (PAPERS.md) demonstrates that most of
disagg's interference isolation is recoverable *inside* one accelerator
by proactively partitioning prefill and decode work; FlowKV shows
load-aware phase scheduling is what keeps that split honest under
shifting traffic. This module is that middle mode: instead of the static
``admit_batches_per_tick`` cap, each scheduler tick gets a closed-loop
**prefill token budget** that shrinks when decode-phase latency burns the
SLO error budget and grows back when ITL has headroom.

The control law is AIMD with hysteresis, evaluated on the engine's
injectable clock (the fake-clock state-machine tests drive it):

  * **Signal.** ``observe_decode`` turns the reap cadence into per-token
    inter-token-latency samples (inter-reap gap ÷ tokens emitted per
    sequence). The burn rate over a sliding window is the SRE-workbook
    shape: ``breach_fraction ÷ (1 − slo_target)`` against ``itl_slo_s``.
    An external ``burn_source`` (the PR 13 SLO plane's decode-phase
    ``slo_burn_rate``) overrides the internal estimate when wired.
  * **Shrink.** ``burn ≥ burn_shrink`` for ``shrink_after`` spaced
    evaluations → multiplicative decrease (× ``shrink_factor``), floored
    at ``floor_tokens`` — the starvation floor that keeps TTFT bounded no
    matter how hot decode runs.
  * **Grow.** ``burn ≤ burn_grow`` for ``grow_after`` spaced evaluations
    → additive increase (+ ``grow_tokens``), capped at
    ``ceiling_tokens``. The dead band between the two thresholds is the
    hysteresis: oscillating load parks the budget instead of flapping it.
  * **Brownout rung.** ``set_pressure(True)`` (wired from the PR 8
    overload ladder) slams the effective budget to the floor BEFORE the
    controller ever clamps ``max_tokens`` or sheds — shrinking prefill is
    the cheapest lever on the ladder, so it fires first and releases
    last.

Per tick, ``tick_grant`` hands the scheduler the number of prefill chunk
tokens it may spend this tick. A tick with no decode work gets an
unbounded grant — the budget exists to protect decode ITL, and with
nothing to protect, throttling prefill would only burn TTFT (and an
idle-tick token budget would busy-spin the loop). Overdraft within one
chunk round is settled as debt against the next tick, so the chunk
boundary stays the clean resume point the determinism suite pins.

Every adjustment passes the ``engine.budget.apply`` fault seam
(runtime/fault_names.py, DYN006): an injected fault skips that
adjustment — counted, evented, budget untouched — and can never corrupt
the budget or take the tick loop down. Events reach the engine's flight
ring through the ``on_event`` callback (an engine-bound method, so the
DYN005 single-writer discipline holds); this module never owns a ring.

``observe_decode`` is on the DYN002 decode hot path (called from
``_reap_burst``): deque appends and arithmetic only — no logging, no
locks, no device access. ``TickBudgeter.evaluate`` is a blessed DYN002
boundary (analysis/config.py) so the control law can log its decisions
without dragging the whole module into the ban list.
"""

from __future__ import annotations

import collections
import time
from dataclasses import dataclass
from typing import Callable, Optional

from dynamo_tpu.runtime import fault_names
from dynamo_tpu.runtime.faults import fault_point
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# Budget states, ordered by how hard prefill is being squeezed. Gauge
# values ARE the wire form (dashboards alert on state >= 3).
BUDGET_STATE_OFF = 0  # budgeter disabled: aggregated mode, no bound
BUDGET_STATE_THROUGHPUT = 1  # at the ceiling: ITL has headroom
BUDGET_STATE_ADAPTIVE = 2  # mid-band: the control law is working
BUDGET_STATE_FLOOR = 3  # starvation floor / brownout squeeze

BUDGET_STATE_NAMES = {
    BUDGET_STATE_OFF: "off",
    BUDGET_STATE_THROUGHPUT: "throughput",
    BUDGET_STATE_ADAPTIVE: "adaptive",
    BUDGET_STATE_FLOOR: "floor",
}


@dataclass(frozen=True)
class TickBudgetConfig:
    """Budgeter knobs (docs/design_docs/disagg_serving.md has the full
    table). The policy knob is ``policy``: 0.0 parks the initial budget
    at the starvation floor (strict ITL), 1.0 at the ceiling (max
    throughput); the control law moves it from there."""

    # Starvation floor: the prefill tokens a tick may ALWAYS spend, no
    # matter how hot decode burns — bounds TTFT under sustained squeeze.
    floor_tokens: int = 512
    # Ceiling: past this, more prefill per tick no longer hides behind
    # the decode readback the PR 3 pipeline overlaps.
    ceiling_tokens: int = 8192
    # Where between floor and ceiling the budget starts (and what the
    # gauge reports until the first adjustment).
    policy: float = 0.5
    # Decode-phase ITL SLO the internal burn estimate breaches against;
    # None = the budgeter only moves on an external burn_source.
    itl_slo_s: Optional[float] = None
    # SLO target for the burn denominator: burn = breach_fraction /
    # (1 - slo_target). 0.9 → 10% error budget.
    slo_target: float = 0.9
    # Burn thresholds. >= burn_shrink shrinks, <= burn_grow grows; the
    # band between them is the hysteresis dead zone (no flapping on
    # oscillating load).
    burn_shrink: float = 1.0
    burn_grow: float = 0.5
    # AIMD: multiplicative decrease, additive increase.
    shrink_factor: float = 0.5
    grow_tokens: int = 512
    # Evaluations closer together than this don't advance the streaks —
    # a hysteresis step denominates TIME, not tick rate (same contract
    # as OverloadConfig.min_eval_interval_s).
    eval_interval_s: float = 0.25
    # Spaced evaluations over threshold before acting. shrink_after=1 →
    # a burn spike shrinks the budget within ONE evaluation window;
    # growth is deliberately slower.
    shrink_after: int = 1
    grow_after: int = 4
    # Sliding ITL sample window for the internal burn estimate, how many
    # samples it needs before it is trusted, and the staleness horizon
    # (an idle engine must decay to "unknown", not testify forever).
    itl_window: int = 64
    min_itl_samples: int = 4
    itl_sample_ttl_s: float = 60.0


class TickBudgeter:
    """Closed-loop per-tick prefill token budget.

    Threading contract: every method runs on the engine's event loop
    (the same single-writer discipline as the engine flight ring).
    ``clock`` is injectable so the state-machine tests drive hysteresis
    with a fake clock. ``burn_source`` () -> Optional[float] overrides
    the internal burn estimate when it returns a number. ``on_event``
    (kind, **fields) is the engine's flight-ring append seam.
    """

    def __init__(
        self,
        config: Optional[TickBudgetConfig] = None,
        *,
        clock: Callable[[], float] = time.monotonic,
        burn_source: Optional[Callable[[], Optional[float]]] = None,
        on_event: Optional[Callable[..., None]] = None,
    ) -> None:
        self.config = config or TickBudgetConfig()
        cfg = self.config
        if cfg.floor_tokens > cfg.ceiling_tokens:
            raise ValueError(
                f"floor_tokens {cfg.floor_tokens} > ceiling_tokens "
                f"{cfg.ceiling_tokens}"
            )
        self._clock = clock
        self._burn_source = burn_source
        self._on_event = on_event
        span = cfg.ceiling_tokens - cfg.floor_tokens
        policy = min(1.0, max(0.0, cfg.policy))
        self._budget = cfg.floor_tokens + int(policy * span)
        self._pressure = False  # brownout squeeze active
        self._debt = 0  # overdraft carried into the next tick
        self._shrink_streak = 0
        self._grow_streak = 0
        self._last_eval_at: Optional[float] = None
        # (observed-at, itl_s) pairs; maxlen bounds memory, the TTL
        # prune in _burn bounds staleness.
        self._itl_samples: "collections.deque" = collections.deque(
            maxlen=cfg.itl_window
        )
        self._last_ready_at: Optional[float] = None
        # Lifetime counters (stats()/bench surfaces).
        self.shrinks = 0
        self.grows = 0
        self.skipped_applies = 0
        self.rollovers = 0
        self.rolled_tokens = 0
        self.squeezes = 0

    # -- observability -------------------------------------------------------

    @property
    def budget_tokens(self) -> int:
        """The EFFECTIVE per-tick budget: the brownout squeeze pins it
        at the floor regardless of what the control law last chose."""
        if self._pressure:
            return self.config.floor_tokens
        return self._budget

    @property
    def pressure(self) -> bool:
        return self._pressure

    @property
    def state(self) -> int:
        cfg = self.config
        eff = self.budget_tokens
        if eff <= cfg.floor_tokens:
            return BUDGET_STATE_FLOOR
        if eff >= cfg.ceiling_tokens:
            return BUDGET_STATE_THROUGHPUT
        return BUDGET_STATE_ADAPTIVE

    def snapshot(self) -> dict:
        return {
            "budget_tokens": self.budget_tokens,
            "state": BUDGET_STATE_NAMES[self.state],
            "pressure": self._pressure,
            "debt": self._debt,
            "shrinks": self.shrinks,
            "grows": self.grows,
            "skipped_applies": self.skipped_applies,
            "rollovers": self.rollovers,
            "rolled_tokens": self.rolled_tokens,
            "squeezes": self.squeezes,
            "burn": self._burn(),
        }

    def _emit(self, kind: str, **fields) -> None:
        if self._on_event is not None:
            self._on_event(kind, **fields)

    # -- signal (DYN002 hot path: arithmetic + deque only) -------------------

    def observe_decode(
        self,
        dur_s: float,
        occupancy: int,
        tokens: int,
        *,
        now: Optional[float] = None,
    ) -> None:
        """One reaped decode burst → per-token ITL samples. Preferred
        signal is the inter-reap gap (what a stream actually waits
        between tokens, prefill stalls included); the burst's own
        duration is the fallback when the reap cadence has a hole."""
        if tokens <= 0:
            return
        t = self._clock() if now is None else now
        per_seq = tokens / max(occupancy, 1)
        if self._last_ready_at is not None and t > self._last_ready_at:
            itl = (t - self._last_ready_at) / max(per_seq, 1.0)
        else:
            itl = dur_s / max(per_seq, 1.0)
        self._last_ready_at = t
        self._itl_samples.append((t, itl))

    def note_idle(self) -> None:
        """The engine went idle: the next reap's inter-reap gap would
        span the idle period — reset the cadence clock instead."""
        self._last_ready_at = None

    def _burn(self) -> Optional[float]:
        """Error-budget burn rate: external source wins; else breach
        fraction over the sample window ÷ (1 − slo_target)."""
        if self._burn_source is not None:
            try:
                ext = self._burn_source()
            except Exception:
                logger.exception("tick budget burn source failed")
                ext = None
            if ext is not None:
                return float(ext)
        cfg = self.config
        if cfg.itl_slo_s is None:
            return None
        horizon = self._clock() - cfg.itl_sample_ttl_s
        while self._itl_samples and self._itl_samples[0][0] < horizon:
            self._itl_samples.popleft()
        if len(self._itl_samples) < cfg.min_itl_samples:
            return None
        breaches = sum(
            1 for _, v in self._itl_samples if v > cfg.itl_slo_s
        )
        frac = breaches / len(self._itl_samples)
        return frac / max(1.0 - cfg.slo_target, 1e-6)

    # -- control law ---------------------------------------------------------

    def evaluate(self) -> int:
        """Run one control-law evaluation; returns the effective budget.
        Calls closer together than eval_interval_s are no-ops (streaks
        untouched) — hysteresis denominates time, not tick rate."""
        cfg = self.config
        now = self._clock()
        if (
            self._last_eval_at is not None
            and now - self._last_eval_at < cfg.eval_interval_s
        ):
            return self.budget_tokens
        self._last_eval_at = now
        burn = self._burn()
        if burn is None:
            # No evidence either way: park the streaks (a cold window
            # must neither shrink nor grow the budget).
            self._shrink_streak = 0
            self._grow_streak = 0
            return self.budget_tokens
        if burn >= cfg.burn_shrink:
            self._shrink_streak += 1
            self._grow_streak = 0
            if self._shrink_streak >= cfg.shrink_after:
                self._shrink_streak = 0
                self._apply(
                    max(
                        cfg.floor_tokens,
                        int(self._budget * cfg.shrink_factor),
                    ),
                    "shrink",
                    burn,
                )
        elif burn <= cfg.burn_grow:
            self._grow_streak += 1
            self._shrink_streak = 0
            if self._grow_streak >= cfg.grow_after:
                self._grow_streak = 0
                self._apply(
                    min(
                        cfg.ceiling_tokens,
                        self._budget + cfg.grow_tokens,
                    ),
                    "grow",
                    burn,
                )
        else:
            # Dead band: hold. Streaks reset so oscillation around the
            # band cannot accumulate into a flap.
            self._shrink_streak = 0
            self._grow_streak = 0
        return self.budget_tokens

    def _apply(self, new_budget: int, kind: str, burn: float) -> None:
        if new_budget == self._budget:
            return
        try:
            # Chaos seam (DYN006): an injected fault models the control
            # law dying — skip THIS adjustment, never corrupt the budget.
            fault_point(fault_names.ENGINE_BUDGET_APPLY, kind=kind)
        except Exception:
            self.skipped_applies += 1
            self._emit(
                "budget_skip", op=kind, frm=self._budget, to=new_budget
            )
            return
        old, self._budget = self._budget, new_budget
        if kind == "shrink":
            self.shrinks += 1
        else:
            self.grows += 1
        self._emit(
            f"budget_{kind}", frm=old, to=new_budget, burn=round(burn, 3)
        )
        logger.debug(
            "tick budget %s %d -> %d (burn %.2f)", kind, old, new_budget, burn
        )

    # -- brownout rung -------------------------------------------------------

    def set_pressure(self, on: bool) -> None:
        """Overload-ladder lever: squeeze the effective budget to the
        starvation floor (before the ladder clamps max_tokens or sheds)
        / release it. Idempotent; a release re-enters the control law
        from the floor, not from the pre-squeeze budget — growth has to
        be re-earned with clean evaluations."""
        if on == self._pressure:
            return
        self._pressure = on
        self._shrink_streak = 0
        self._grow_streak = 0
        if on:
            self.squeezes += 1
            self._budget = self.config.floor_tokens
            self._emit("budget_squeeze", to=self.config.floor_tokens)
        else:
            self._emit("budget_release", frm=self.config.floor_tokens)

    # -- per-tick grant ------------------------------------------------------

    def tick_grant(self, decode_active: bool) -> Optional[int]:
        """Prefill chunk tokens this tick may spend. None = unbounded
        (no decode work to protect — throttling would only burn TTFT and
        busy-spin the idle loop). Overdraft from the previous tick is
        settled here before anything is granted."""
        self.evaluate()
        if not decode_active:
            return None
        budget = self.budget_tokens
        grant = max(0, budget - self._debt)
        self._debt = max(0, self._debt - budget)
        return grant

    def add_debt(self, tokens: int) -> None:
        """Overdraft: the last chunk round of a tick may overshoot the
        grant (the round is atomic); the excess is paid off next tick."""
        if tokens > 0:
            self._debt += tokens

    def note_rollover(self, unspent: int) -> None:
        """A watermark hold left budget unspent and the tick went to
        decode instead of idling — counted so the double-stall
        regression stays visible."""
        if unspent > 0:
            self.rollovers += 1
            self.rolled_tokens += unspent
