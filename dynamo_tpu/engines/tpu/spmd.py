"""SPMD leader/follower execution for a multi-process JaxEngine.

One logical worker spans N processes (parallel/multihost.py): the leader
(process 0) runs the scheduler + endpoint; its DeviceRunner mirrors every
device-program invocation over the op channel
(runtime/network/spmd_channel.py); followers run :func:`follow`, re-issuing
the identical invocation so every process enters the global-mesh jit
together — the JAX-native version of the reference's DP leader /
non-leader worker ranks (components/src/dynamo/vllm/main.py:67-78).

Determinism contract: a follower's runner is constructed with the same
JaxEngineArgs/params/seed as the leader's, and ops are applied in channel
order — so jitted-program variant selection, RNG-step counters, processor
state, and cache donation stay in lockstep with zero extra coordination.
"""

from __future__ import annotations

from typing import Any

from dynamo_tpu.runtime.network.spmd_channel import SpmdBroadcaster, SpmdFollower
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def follow(runner: Any, follower: SpmdFollower) -> None:
    """Blocking follower loop: execute the leader's op stream until stop.

    Runs the runner's device methods synchronously on this thread (the
    follower process has no scheduler, no endpoint, no asyncio engine loop
    — it exists to contribute its devices to the collectives). The runner's
    own mirroring is a no-op here (no broadcaster is set on followers).
    """
    while True:
        op, args = follower.recv()
        if op == "stop":
            logger.info("SPMD follower: leader closed the channel")
            return
        try:
            if op == "decode_state":
                # Dispatch only — the leader owns the readback (reap). The
                # follower's state carry (tokens/pos) advances inside the
                # dispatch, so the dispatch/reap split stays lockstep: both
                # processes issue the identical program from identical
                # device state, and the follower never blocks on results.
                runner.decode_dispatch(
                    int(args["nb"]),
                    want_logprobs=bool(args["want_logprobs"]),
                    use_procs=bool(args["use_procs"]),
                )
            elif op == "slot_sync":
                runner.sync_slots(list(args["slots"]), dict(args["rows"]))
            elif op == "table_sync":
                runner.sync_tables(list(args["slots"]), args["rows"])
            elif op == "step":
                runner.run_step(**args)
            elif op == "spec":
                runner.run_spec(**args)
            elif op == "gather":
                runner.gather_blocks(list(args["ids"]))
            elif op == "gather_wire":
                # Pool-native gather: the follower joins the collective;
                # only the leader reads the result back.
                runner.gather_blocks_wire(list(args["ids"]))
            elif op == "scatter":
                runner.scatter_blocks(
                    list(args["ids"]), args["k_blocks"], args["v_blocks"]
                )
            elif op == "scatter_wire":
                from dynamo_tpu.disagg.wire import KvWireBlocks

                runner.scatter_blocks_wire(
                    list(args["ids"]),
                    KvWireBlocks(
                        dtype="int8",
                        k=args["k_q8"], v=args["v_q8"],
                        k_scale=args["k_s"], v_scale=args["v_s"],
                    ),
                )
            elif op == "proc_reset":
                runner.proc_reset_slot(
                    int(args["slot"]), args["prompt_ids"], args["generated"]
                )
            elif op == "proc_count":
                runner.proc_count(int(args["slot"]), int(args["token"]))
            elif op == "lora_install":
                from dynamo_tpu.lora.loader import LoRAAdapter

                adapter = LoRAAdapter(
                    name=args["name"], rank=int(args["rank"]),
                    scaling=float(args["scaling"]),
                    weights={
                        t: (A, B) for t, (A, B) in args["weights"].items()
                    },
                )
                runner.install_adapter(adapter)
            elif op == "lora_remove":
                runner.remove_adapter(args["name"])
            elif op == "sleep":
                runner.sleep_device(int(args.get("level", 1)))
            elif op == "wake":
                runner.wake_device()
            else:
                raise ValueError(f"unknown SPMD op {op!r}")
        except Exception:
            # A follower that diverges can only poison the collective —
            # surface loudly and exit; jax.distributed's heartbeat tears
            # down the rest of the worker group.
            logger.exception("SPMD follower failed applying op %r", op)
            raise


FOLLOWER_LOSS_EXIT = 13  # distinct rc: supervisor restarts the group


def make_broadcaster(
    port: int, num_followers: int, *, die_on_follower_loss: bool = True
) -> SpmdBroadcaster:
    bcast = SpmdBroadcaster(port, num_followers)
    bcast.wait_for_followers()
    if die_on_follower_loss:
        # A dead follower is unrecoverable (it missed ops; the group's
        # collectives can never complete) AND undetectable from the op
        # stream alone — the leader's next dispatch blocks inside a
        # collective. Death-watch + immediate exit is the SPMD-correct
        # fail-fast (the reference's worker ranks die together on NCCL
        # abort; ref lib/llm/src/migration.rs:24 re-routes in-flight work
        # at the frontend tier); the supervisor (pod group restart,
        # deploy/pod_connector.py) brings the whole group back.
        def _die(i: int, exc: BaseException) -> None:
            import os as _os

            logger.error(
                "SPMD follower %d died (%s): worker group unrecoverable, "
                "exiting rc=%d for group restart", i, exc, FOLLOWER_LOSS_EXIT,
            )
            _os._exit(FOLLOWER_LOSS_EXIT)

        bcast.start_death_watch(_die)
    return bcast


def make_follower(leader_host: str, port: int) -> SpmdFollower:
    return SpmdFollower(leader_host, port)
