"""Admission pipeline: waiting queue -> prefilled, installed sequences.

Split from the engine monolith (the engine owns the scheduler loop; this
owns the admission policy): batched prefix-cache matching + block leasing,
joint chunked prefill over a [Bp, C] ragged batch, failure containment
(poisoned-request quarantine with a systemic-failure breaker), and slot
installation including logits-processor bookkeeping.

Reference parity: the role of vLLM's scheduler admission + prefix-cache
lookup behind components/src/dynamo/vllm (SURVEY §2.2), restructured
around ONE batched device dispatch per chunk round (B=1 prefill wastes the
MXU; measured 16× rows for 2.4× cost on the v5e).
"""

from __future__ import annotations

import asyncio
import math
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from dynamo_tpu.engines.tpu.runner import _next_pow2
from dynamo_tpu.runtime import lifecycle
from dynamo_tpu.runtime.kv_reuse_observe import global_plane as kv_reuse_plane
from dynamo_tpu.tokens.blocks import adapter_salt, compute_block_hashes

from dynamo_tpu.llm.protocols.common import (
    BackendOutput,
    FinishReason,
    PreprocessedRequest,
)
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class PendingPrefill:
    """A joint chunked prefill's full loop state: the loop-invariant
    arrays built once per batch (_begin_prefill) plus per-row progress.
    The budgeted tick (engine._admit_tick_budgeted) parks one of these
    when the prefill token grant runs out mid-batch — a chunk boundary is
    a clean resume point (positions, tables and sampling arrays are
    exactly what the next round needs, and the position-keyed sampling
    RNG draws the identical first tokens on resume), which is what keeps
    budgeter-on and budgeter-off token streams bit-identical."""

    batch: List[Tuple[Any, Any]]
    prompts: List[List[int]]
    pos: List[int]
    first: List[Optional[Tuple[int, float, Optional[list]]]]
    want_top: bool
    tables: np.ndarray
    temp: np.ndarray
    topk: np.ndarray
    topp: np.ndarray
    adapter: np.ndarray
    salts: np.ndarray
    procs: Optional[tuple]
    mm_embeds: Optional[np.ndarray]
    mm_slot_of: Optional[np.ndarray]
    rows: int
    Bp: int


class Admitter:
    """Engine-attached admission pipeline (state lives on the engine)."""

    def __init__(self, engine: Any) -> None:
        self.e = engine

    async def _admit_batch(self) -> int:
        """Admit + prefill up to ``prefill_batch`` waiting sequences in ONE
        batched device dispatch per chunk round. Returns how many were
        installed into the decode batch.

        Failure containment matches the round-2 breaker semantics: a
        poisoned batch is retried per-sequence (one retry then an error
        stream); the cross-request failure streak still detects systemic
        breakage and fails the engine terminally.
        """
        e = self.e

        free_slots = [i for i, s in enumerate(e._slots) if s is None]
        # Pending handoff adoptions (drain plane) hold a slot reservation:
        # adopt_handoff already promised the peer capacity, and the
        # scheduler loop installs adoptions before admission each tick —
        # local admission taking the last free slot would strand an
        # adopted LIVE stream (client mid-decode) behind the whole queue.
        for _ in e._adoptions:
            if free_slots:
                free_slots.pop()
        if not free_slots or not e._waiting:
            return 0
        batch: List[Tuple[Any, Any]] = []
        limit = min(len(free_slots), e.args.prefill_batch)
        # The dual of the reservation above: while this batch is being
        # prepared/prefilled (both await), adopt_handoff must count its
        # slots-to-be as taken (engine._admitting) or it accepts a
        # handoff into a slot this batch is about to install into.
        try:
            while e._waiting and len(batch) < limit:
                seq = e._waiting[0]
                # Expired/cancelled work sheds AT DEQUEUE, before any pool
                # or prefill spend — deadline expiries surface as a typed
                # error (overload armor: an already-dead request must
                # never reach the device).
                if seq.context.stopped:
                    e._waiting.popleft()
                    e._shed_expired(seq)
                    continue
                # Backpressure: past the high watermark, admitting trades
                # one queued request for a preemption storm against the
                # running ones — hold the queue and let decode drain
                # instead. Only with live occupants: an idle engine always
                # admits (the watermark measures contention, not fit).
                if (
                    e.pool.usage >= e.args.admit_kv_high_watermark
                    and any(s is not None for s in e._slots)
                ):
                    break
                has_mm = bool((seq.request.extra or {}).get("mm_embeds"))
                if has_mm and batch:
                    break  # multimodal rows carry their own embed arrays: solo batch
                e._waiting.popleft()
                e._admitting = len(batch) + 1
                try:
                    prep = await e._prepare_admission(seq)
                except asyncio.CancelledError:
                    e._waiting.appendleft(seq)
                    raise
                except Exception as exc:
                    e._contain_admission_failure([seq], exc)
                    return len(batch) if not batch else await e._finish_admission(batch)
                if prep is None:  # pool dry; seq was requeued to the front
                    break
                batch.append((seq, prep))
                e._admitting = len(batch)
                if has_mm:
                    break
            if not batch:
                return 0
            return await e._finish_admission(batch)
        finally:
            e._admitting = 0

    async def _finish_admission(self, batch: "List[Tuple[Any, Any]]") -> int:
        return await self._run_prefill(self._begin_prefill(batch))

    async def _run_prefill(self, pending: "PendingPrefill") -> int:
        """Run (or resume) a prefill's chunk rounds to completion or to
        budget exhaustion, then install. Returns rows installed; 0 covers
        both containment (batch ejected/requeued) and a budget park (the
        pending state is stashed on the engine, blocks still pinned)."""
        e = self.e
        try:
            done = await self._prefill_rounds(pending)
        except asyncio.CancelledError:
            for seq, prep in pending.batch:
                e.pool.release(prep.ids, prep.hashes[: prep.matched])
                e._requeue(seq)
            raise
        except Exception as exc:
            for seq, prep in pending.batch:
                e.pool.release(prep.ids, prep.hashes[: prep.matched])
                seq.block_ids = []
                seq.block_hashes = []
            e._contain_admission_failure([s for s, _ in pending.batch], exc)
            return 0
        if not done:
            # Tick budget exhausted at a chunk boundary: park. Blocks stay
            # pinned and per-row positions are kept — the engine resumes
            # this exact state with the next tick's grant, ahead of any
            # new admission (FIFO order is preserved).
            e._pending_prefill = pending
            e._record_budget_event(
                "prefill_pause",
                rows=pending.rows,
                done=sum(pending.pos),
                total=sum(len(p) for p in pending.prompts),
            )
            return 0
        e._admission_failure_streak = 0
        free_iter = (i for i, s in enumerate(e._slots) if s is None)
        for (seq, prep), f in zip(pending.batch, pending.first):
            tok, logp, top = f
            e._install(seq, prep, next(free_iter), tok, logp, top)
        return len(pending.batch)

    def _contain_admission_failure(self, seqs: "List[Any]", exc: Exception) -> None:
        """Per-request retry-once-then-eject; streak detects systemic failure."""
        e = self.e

        for seq in seqs:
            seq.admission_failures += 1
            if seq.admission_failures >= 2:
                logger.exception(
                    "ejecting request %s after %d admission failures",
                    seq.request.request_id, seq.admission_failures,
                )
                seq.queue.put_nowait(
                    BackendOutput(
                        error=f"admission failed: {type(exc).__name__}: {exc}",
                        finish_reason=FinishReason.ERROR,
                    )
                )
            else:
                logger.exception(
                    "admission of %s failed; will retry once",
                    seq.request.request_id,
                )
                e._waiting.appendleft(seq)
        e._admission_failure_streak += 1
        if e._admission_failure_streak >= 6:
            e._fail_terminally(exc)

    async def _prepare_admission(self, seq: Any) -> "Optional[Any]":
        """Pool work for one sequence: salting, prefix match, allocation.
        Returns None (after requeueing the sequence) when the pool is dry."""
        e = self.e

        args = e.args
        prompt = seq.all_tokens  # includes regenerated tokens after preemption
        n_blocks_prompt = math.ceil(len(prompt) / args.block_size)

        # Multimodal splice inputs (multimodal/handlers.py): packed patch
        # embeddings + a prompt-position → embedding-row map.
        mm_embeds: Optional[np.ndarray] = None
        mm_slot_of: Optional[np.ndarray] = None
        mm = seq.request.extra or {}
        if "mm_embeds" in mm:
            from dynamo_tpu.disagg.handlers import unpack_array

            mm_embeds = unpack_array(mm["mm_embeds"]).astype(np.float32)
            per_image = int(mm.get("mm_tokens_per_image", 0))
            mm_slot_of = np.full(len(prompt), -1, dtype=np.int32)
            row = 0
            for start in mm.get("mm_positions", []):
                for j in range(per_image):
                    if start + j < len(prompt):
                        mm_slot_of[start + j] = row
                    row += 1

        # Salted hashing: adapter ⊕ image content — neither LoRA K/V nor
        # image-conditioned K/V may cross-pollinate the base prefix cache.
        seq.hash_salt = adapter_salt(seq.request.lora_name)
        if mm_embeds is not None:
            import xxhash

            seq.hash_salt ^= xxhash.xxh3_64(mm_embeds.tobytes()).intdigest()

        hashes: List[int] = []
        matched = 0
        ids: List[int] = []
        if args.enable_prefix_caching:
            hashes = compute_block_hashes(
                prompt, args.block_size, salt=seq.hash_salt
            )
            pf = getattr(seq, "kv_prefetch", None)
            stall = 0.0
            if e.kvbm is not None and hashes and pf is not None:
                # Speculative lease (docs/design_docs/kv_prefetch.md): the
                # onboard walk ran while this request sat in the queue, so
                # joining here stalls only for the un-overlapped remainder
                # — walk time minus this stall is the TTFT the speculation
                # bought, recorded by claim() below.
                t_wait = time.monotonic()
                await pf.wait()
                stall = time.monotonic() - t_wait
                if pf.settled:
                    # The walk died, was revoked, or found nothing — no
                    # lease is held: take the serial path below exactly
                    # like hintless traffic.
                    seq.kv_prefetch = None
                    pf = None
                elif pf.source:
                    seq.kv_hit_tier = pf.source
            if e.kvbm is not None and hashes and pf is None:
                # Serial fallback (unrouted/hintless traffic): onboard from
                # the lower tiers (G2/G3) anything that extends the device
                # prefix match (ref: KVBM onboard-before-prefill, §3.4).
                n_dev = e.pool.match_prefix(hashes)
                if n_dev < len(hashes):
                    try:
                        if await e.kvbm.onboard(hashes):
                            # Hit attribution for the KV-reuse plane: the
                            # match was extended from a lower tier.
                            seq.kv_hit_tier = (
                                getattr(e.kvbm, "last_onboard_source", None)
                                or "host"
                            )
                    except Exception:
                        logger.exception("KV onboard failed; prefilling locally")
            matched, ids = e.pool.pin_prefix(hashes)
            if pf is not None:
                # Claim AFTER our own pin: the lease's pins release with
                # the blocks already re-held, so their refcounts never dip
                # to zero (and the pool can never evict them) in between.
                pf.claim(stall_s=stall)
                seq.kv_prefetch = None
        matched_tokens = min(matched * args.block_size, len(prompt) - 1)

        # Watermark headroom so running decodes can still grow.
        headroom = (
            int(args.num_kv_blocks * args.watermark)
            if any(s is not None for s in e._slots)
            else 0
        )
        need = n_blocks_prompt - len(ids) + 1 + headroom
        if need > e.pool.free_blocks:
            e.pool.release(ids, hashes[:matched])
            e._requeue(seq)
            return None
        while len(ids) < n_blocks_prompt:
            b = e.pool.alloc()
            if b is None:  # raced below watermark; put everything back
                e.pool.release(ids, hashes[:matched])
                e._requeue(seq)
                return None
            ids.append(b)
        seq.block_ids = ids
        seq.block_hashes = hashes[:matched]
        return _prep_cls()(
            ids=ids,
            hashes=hashes,
            matched=matched,
            matched_tokens=matched_tokens,
            sp=e._sampling_of(seq.request),
            adapter_id=e._lora_index.get(seq.request.lora_name or "", 0),
            mm_embeds=mm_embeds,
            mm_slot_of=mm_slot_of,
            procs=e._procs_of(seq.request),
        )

    async def _prefill_batch(
        self, batch: "List[Tuple[Any, Any]]"
    ) -> List[Tuple[int, float]]:
        """Joint chunked prefill to COMPLETION — the tick budget does not
        apply (callers outside the budgeted admission path want the whole
        batch: tests, checkpoint warmup). Returns each row's
        (first_token, logprob, top)."""
        e = self.e
        pending = self._begin_prefill(batch)
        saved, e._tick_budget_left = e._tick_budget_left, None
        try:
            await self._prefill_rounds(pending)
        finally:
            e._tick_budget_left = saved
        return pending.first  # type: ignore[return-value]

    def _begin_prefill(self, batch: "List[Tuple[Any, Any]]") -> PendingPrefill:
        """Per-batch prefill preamble: lifecycle/ROI stamps plus every
        loop-invariant device array, captured as a PendingPrefill so the
        chunk rounds can pause and resume across ticks."""
        e = self.e
        args = e.args
        rows = len(batch)
        prompts = [seq.all_tokens for seq, _ in batch]
        pos = [prep.matched_tokens for _, prep in batch]
        for seq, prep in batch:
            seq.t_prefill_start = time.monotonic()
            lifecycle.record(
                seq.request.request_id, "prefill_start",
                context=seq.context,
                prompt_tokens=len(seq.all_tokens),
                cached_tokens=prep.matched_tokens,
            )
            # Cache-ROI attribution: one feed per admitted request, on the
            # engine side only (the router feeds popularity, not ROI).
            seq.kv_roi = kv_reuse_plane().note_request(
                anchor=prep.hashes[prep.matched - 1] if prep.matched else None,
                cached_tokens=prep.matched_tokens,
                recomputed_tokens=len(seq.all_tokens) - prep.matched_tokens,
                tier=getattr(seq, "kv_hit_tier", "device"),
                trace_id=lifecycle.trace_id_of(seq.context),
            )
        first: List[Optional[Tuple[int, float, Optional[list]]]] = [None] * rows
        # Any row asking for top-N logprobs routes the batch through the
        # top-variant prefill program so the FIRST generated token carries
        # alternatives too (not just the fused-decode tokens).
        want_top = any(
            (seq.request.sampling.logprobs or 0) > 0 for seq, _ in batch
        )

        nb_needed = max(len(prep.ids) for _, prep in batch)
        nb_bucket = min(_next_pow2(nb_needed), args.max_blocks_per_seq)
        Bp = _next_pow2(rows)
        tables = np.zeros((Bp, nb_bucket), dtype=np.int32)
        temp = np.ones(Bp, dtype=np.float32)
        topk = np.zeros(Bp, dtype=np.int32)
        topp = np.ones(Bp, dtype=np.float32)
        adapter = np.zeros(Bp, dtype=np.int32)
        salts = np.zeros(Bp, dtype=np.int32)
        for r, (seq_r, prep) in enumerate(batch):
            tables[r, : len(prep.ids)] = prep.ids
            temp[r], topk[r], topp[r] = prep.sp
            adapter[r] = prep.adapter_id
            salts[r] = seq_r.salt
        procs = None
        if any(prep.procs is not None for _, prep in batch):
            from dynamo_tpu.ops.logits_process import MAX_BIAS_SLOTS, prompt_hot

            V = e.config.vocab_size
            minp = np.zeros(Bp, dtype=np.float32)
            rep = np.ones(Bp, dtype=np.float32)
            pres = np.zeros(Bp, dtype=np.float32)
            freq = np.zeros(Bp, dtype=np.float32)
            bias_ids = np.full((Bp, MAX_BIAS_SLOTS), -1, dtype=np.int32)
            bias_vals = np.zeros((Bp, MAX_BIAS_SLOTS), dtype=np.float32)
            pmask = np.zeros((Bp, V), dtype=np.bool_)
            for r, (seq_r, prep) in enumerate(batch):
                if prep.procs is None:
                    continue
                p = prep.procs
                minp[r], rep[r], pres[r], freq[r] = p.minp, p.rep, p.pres, p.freq
                bias_ids[r] = p.bias_ids
                bias_vals[r] = p.bias_vals
                # all_tokens (not just the prompt): for preempted re-prefills
                # the repetition penalty must keep covering already-generated
                # tokens. (pres/freq at this single re-sample are approximated
                # as zero; exact history is restored at _install.)
                pmask[r] = prompt_hot(seq_r.all_tokens, V)
            procs = (minp, rep, pres, freq, bias_ids, bias_vals, pmask)
        # Multimodal rows run solo (rows == 1), so row 0's arrays suffice.
        mm_embeds = batch[0][1].mm_embeds if rows == 1 else None
        mm_slot_of = batch[0][1].mm_slot_of if rows == 1 else None
        return PendingPrefill(
            batch=batch, prompts=prompts, pos=pos, first=first,
            want_top=want_top, tables=tables, temp=temp, topk=topk,
            topp=topp, adapter=adapter, salts=salts, procs=procs,
            mm_embeds=mm_embeds, mm_slot_of=mm_slot_of, rows=rows, Bp=Bp,
        )

    async def _prefill_rounds(self, pending: PendingPrefill) -> bool:
        """Chunk rounds for a (possibly resumed) joint prefill: one
        [Bp, C] dispatch per round with per-row start/len (forward_paged
        supports ragged rows natively). A round is the atomic budget
        unit: the tick-grant check happens BEFORE each round — so one
        round may overdraw, settled as debt by the budgeter — and a pause
        always lands on a chunk boundary. Returns True when every row has
        sampled its first token, False on a budget pause."""
        e = self.e
        args = e.args
        rows = pending.rows
        prompts = pending.prompts
        pos = pending.pos
        first = pending.first
        want_top = pending.want_top
        tables = pending.tables
        temp, topk, topp = pending.temp, pending.topk, pending.topp
        adapter, salts, procs = pending.adapter, pending.salts, pending.procs
        mm_embeds, mm_slot_of = pending.mm_embeds, pending.mm_slot_of
        Bp = pending.Bp

        while any(pos[r] < len(prompts[r]) for r in range(rows)):
            if e._tick_budget_left is not None and e._tick_budget_left <= 0:
                return False
            chunks = [
                prompts[r][pos[r] : pos[r] + args.prefill_chunk] for r in range(rows)
            ]
            c_bucket = min(
                _next_pow2(max(len(c) for c in chunks)), args.prefill_chunk
            )
            tok_arr = np.zeros((Bp, c_bucket), dtype=np.int32)
            start = np.zeros(Bp, dtype=np.int32)
            lens = np.zeros(Bp, dtype=np.int32)
            for r in range(rows):
                ch = chunks[r][:c_bucket]
                tok_arr[r, : len(ch)] = ch
                start[r] = pos[r]
                lens[r] = len(ch)
            mm_chunk = None
            if mm_slot_of is not None:
                mm_chunk = np.full((Bp, c_bucket), -1, dtype=np.int32)
                n0 = int(lens[0])
                mm_chunk[0, :n0] = mm_slot_of[pos[0] : pos[0] + n0]
            # Fresh prefills (no prefix-cache hit, first chunk round) take
            # the dense in-chunk attention program — zero paged reads.
            first_chunk = bool(np.all(start[:rows] == 0))
            t0 = time.monotonic()
            toks, logps, topv, topi = await e._device(
                e._run_step,
                tok_arr, start, lens, tables,
                temp, topk, topp, adapter,
                mm_embeds, mm_chunk, procs, want_top, first_chunk, salts,
            )
            dt = time.monotonic() - t0
            e.step_metrics.observe_prefill(
                # Occupancy counts rows still prefilling this round — short
                # prompts finish earlier chunk rounds and ride along with
                # lens == 0.
                dt,
                int(np.count_nonzero(lens[:rows])),
                int(lens.sum()),
            )
            # Per-token prefill cost EWMA — the basis for the plane's
            # prefill-seconds-saved estimate.
            kv_reuse_plane().note_prefill_cost(dt, int(lens.sum()))
            # Perf ledger: prefill tokens/s per pow2 chunk bucket (the
            # attribution sibling of the decode-shape windows).
            e._perf.observe_prefill(c_bucket, dt, int(lens.sum()))
            if e._tick_budget_left is not None:
                e._tick_budget_left -= int(lens.sum())
            for r in range(rows):
                n = int(lens[r])
                if n == 0:
                    continue
                e.prefill_tokens += n
                pos[r] += n
                if pos[r] >= len(prompts[r]):
                    top = None
                    if topv is not None:
                        top = [
                            (int(topi[r, j]), float(topv[r, j]))
                            for j in range(topv.shape[1])
                        ]
                    first[r] = (int(toks[r]), float(logps[r]), top)
        assert all(f is not None for f in first)
        return True

    def _install(
        self, seq: Any, prep: "Any", slot: int, first_token: int,
        first_logprob: float, first_top: Optional[list] = None,
    ) -> None:
        """Commit fresh prompt blocks and join the decode batch."""
        e = self.e
        args = e.args
        prompt = seq.all_tokens
        if args.enable_prefix_caching:
            full = len(prompt) // args.block_size
            for i in range(prep.matched, full):
                parent = prep.hashes[i - 1] if i else None
                e.pool.commit(prep.ids[i], prep.hashes[i], parent)
                seq.block_hashes.append(prep.hashes[i])
                if e.kvbm is not None:
                    e.kvbm.notify_commit(prep.hashes[i], i + 1, parent=parent)
        # Per-slot device state: ONE shared implementation with the
        # drain plane's _install_adopted (engine._set_slot_state) — any
        # new per-slot sampling field must land there, not here.
        e._set_slot_state(
            seq, slot, pos=len(prompt), block_ids=prep.ids, sp=prep.sp,
            adapter_id=prep.adapter_id, procs=prep.procs,
            tok_mirror=int(first_token),
        )
        if prep.procs is not None:
            # The freshly sampled first token is not in seq.generated yet
            # (emit below appends it): count it on the device now.
            e.runner.proc_count(slot, first_token)
        e._emit_token(seq, first_token, first_logprob, first_top)

    def _sampling_of(self, req: PreprocessedRequest) -> Tuple[float, int, float]:
        e = self.e
        s = req.sampling
        temp = s.temperature if s.temperature is not None else 1.0
        topk = s.top_k if s.top_k is not None and s.top_k > 0 else 0
        topp = s.top_p if s.top_p is not None else 1.0
        return float(temp), int(topk), float(topp)

    def _procs_of(self, req: PreprocessedRequest) -> Optional[Any]:
        """Logits-processor params, or None when the request uses none —
        None keeps the batch on the processor-free compiled programs."""
        e = self.e

        s = req.sampling
        rep = float(s.repetition_penalty) if s.repetition_penalty else 1.0
        pres = float(s.presence_penalty) if s.presence_penalty else 0.0
        freq = float(s.frequency_penalty) if s.frequency_penalty else 0.0
        minp = float(s.min_p) if s.min_p else 0.0
        bias = s.logit_bias
        if rep == 1.0 and pres == 0.0 and freq == 0.0 and minp <= 0.0 and not bias:
            return None
        from dynamo_tpu.ops.logits_process import pack_bias

        ids, vals = pack_bias(bias, e.config.vocab_size)
        return _procprep_cls()(
            minp=minp, rep=rep, pres=pres, freq=freq,
            bias_ids=ids, bias_vals=vals,
        )



def _prep_cls():
    from dynamo_tpu.engines.tpu.engine import _Prep

    return _Prep


def _procprep_cls():
    from dynamo_tpu.engines.tpu.engine import _ProcPrep

    return _ProcPrep
