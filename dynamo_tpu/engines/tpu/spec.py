"""Speculative decoding policy: prompt-lookup (n-gram) proposals + greedy
verify (split from the engine monolith; the engine owns only the hook).

Reference parity: the reference exposes speculative decoding as an engine
flag riding vLLM's implementation (components/src/dynamo/vllm/args.py
speculative config plumbing); here proposals come from a per-sequence
n-gram index over the prompt+generation (prompt-lookup decoding) and
verification is ONE [S, spec_k+1]-token dispatch scoring every position
(llama.forward_paged all_logits). Greedy-only: a tick with sampling /
logprobs / logits-processor requests falls back to the fused decode path.

Measured on the v5e (BENCH_SPEC=ngram, see docs/design_docs/
performance.md): wins on extractive/repetitive workloads where proposals
hit; loses on random-token workloads (every miss costs a dispatch that
fused decode would have spent on decode_steps tokens) — hence the
``tick()`` early-outs that keep the engine on the fused path whenever
nothing proposes.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

# Shared with the decode tick so spec-verify dispatches reuse the same
# pow2 table-width buckets (and their compiled programs' shapes). No
# cycle: engine.py imports this module lazily (the _spec property).
from dynamo_tpu.engines.tpu.engine import table_width_bucket


class NgramSpecDecoder:
    """Engine-attached speculative decoder (state lives on the sequences;
    the device program lives in the runner)."""

    def __init__(self, engine: Any) -> None:
        self.e = engine

    def propose(self, seq: Any) -> List[int]:
        """Prompt-lookup proposal: index new tokens, then continue from the
        most recent earlier occurrence of the trailing n-gram."""
        n = self.e.args.spec_ngram
        toks = seq.all_tokens
        # Incremental index: register every n-gram ENDING at p, excluding
        # the final position (its continuation is what we're predicting).
        for p in range(max(seq.ngram_upto, n - 1), len(toks) - 1):
            seq.ngram_index[tuple(toks[p - n + 1 : p + 1])] = p + 1
        seq.ngram_upto = max(len(toks) - 1, 0)
        if len(toks) < n:
            return []
        cont = seq.ngram_index.get(tuple(toks[-n:]))
        if cont is None:
            return []
        return toks[cont : cont + self.e.args.spec_k]

    def eligible(self, active: List[Any]) -> bool:
        """Sampled requests are served by the rejection-sampling verify
        (ops/sampling.spec_verify_sample — exact target distribution), so
        temperature no longer gates a tick. Logprobs and logits-processor
        rows still fall back to the fused decode path (the verify program
        surfaces neither per-token logprobs nor processor state)."""
        for s in active:
            sp = s.request.sampling
            if sp.logprobs is not None:
                return False
            if self.e._uses_procs[s.slot]:
                return False
        return True

    async def tick(self) -> bool:
        """One verify dispatch over [next_token + proposals]. Returns False
        when this tick is ineligible or nothing proposes — the fused
        decode_steps-per-dispatch path wins whenever speculation has no
        candidates (a 1-token verify would cost decode_steps× the
        dispatches)."""
        e = self.e
        args = e.args
        # Drain the pipelined decode window first: proposals index
        # all_tokens and the verify dispatch reads/writes host-visible
        # pos/tables, so the spec tick must see fully-reconciled state
        # (and must not interleave with a device burst whose carry it
        # would invalidate). The spec dispatch itself bypasses the
        # device-resident carry — the slots it advances are re-synced via
        # the dirty marks below.
        await e._drain_inflight()
        occupied = [s for s in e._slots if s is not None]
        if not occupied:
            return True
        if not self.eligible(occupied):
            return False
        proposals: Dict[int, List[int]] = {
            s.slot: self.propose(s) for s in occupied
        }
        if not any(proposals.values()):
            return False

        C = args.spec_k + 1
        active = e._prepare_decode(C)
        if not active:
            return True
        S = args.max_num_seqs
        tokens = np.zeros((S, C), dtype=np.int32)
        lens = np.zeros(S, dtype=np.int32)
        max_blocks = 1
        for seq in active:
            slot = seq.slot
            prop = proposals.get(slot, [])
            # Never speculate past the model-length cap.
            room = args.max_model_len - int(e._pos[slot]) - 1
            prop = prop[: max(min(len(prop), room), 0)]
            proposals[slot] = prop
            tokens[slot, 0] = seq.next_token
            tokens[slot, 1 : 1 + len(prop)] = prop
            lens[slot] = 1 + len(prop)
            max_blocks = max(
                max_blocks,
                (int(e._pos[slot]) + C - 1) // args.block_size + 1,
            )
        nb_bucket = table_width_bucket(max_blocks, args.max_blocks_per_seq)

        emitted_all, counts = await e._device(
            e._run_spec,
            tokens,
            e._pos.copy(),
            lens,
            e._block_tables[:, :nb_bucket].copy(),
            e._adapter_ids.copy(),
            e._temp.copy(),
            e._topk.copy(),
            e._topp.copy(),
        )
        e.steps += 1
        # The verify dispatch occupied the device: the window before the
        # next fused-decode dispatch is not host-injected gap.
        e._t_last_ready = None
        for seq in list(active):
            if seq.slot < 0:
                continue  # finished by an earlier emit in this loop
            slot = seq.slot
            prop = proposals.get(slot, [])
            n = int(counts[slot])
            emitted = emitted_all[slot, :n].astype(np.int32)
            e.spec_proposed += len(prop)
            e.spec_accepted += n - 1
            e._emit_burst(
                seq, emitted, np.zeros(n, dtype=np.float32),
            )
            if seq.slot >= 0:
                # The verify dispatch advanced this slot outside the
                # decode carry — resync pos/tokens before the next fused
                # decode burst reads the device-resident state.
                e._dirty_state.add(slot)
        return True
