"""The native TPU engine: jit-compiled continuous batching over paged KV.

This replaces the reference's external-engine adapters (vLLM/SGLang/TRT-LLM,
components/src/dynamo/{vllm,sglang,trtllm}) with a first-party JAX engine:
  - paged KV cache in HBM (block_pool.py: prefix reuse + LRU eviction,
    physical block ids ↔ chained hashes, KV events for the router),
  - one compiled forward (models/llama.py forward_paged) serving prefill,
    chunked prefill, and batched decode,
  - an asyncio continuous-batching scheduler (engine.py) with the same
    admission/watermark/preemption semantics as the reference engines.
"""

from dynamo_tpu.engines.tpu.block_pool import BlockPool
from dynamo_tpu.engines.tpu.engine import JaxEngine, JaxEngineArgs

__all__ = ["BlockPool", "JaxEngine", "JaxEngineArgs"]
