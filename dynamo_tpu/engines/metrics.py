"""Engine step-loop metrics (runtime/metric_names.py ALL_ENGINE families).

Reference parity: the reference's backend ForwardPassMetrics / engine-side
Prometheus gauges — but for the step loop itself: how long each device
dispatch takes, how full the batch is, and how many tokens each step moved,
split prefill vs decode. These are the signals the planner's SLA math and
the ROADMAP's autoscaling direction need (step time × occupancy = achieved
throughput; prefill-vs-decode token mix = P/D balance).

One instance per engine object on a private registry (see
runtime/metrics_core.py for why not prometheus_client's global registry);
``render`` plugs into ``SystemStatusServer.register_metrics`` — wired by
``attach_engine`` for any engine exposing a ``step_metrics`` attribute.
"""

from __future__ import annotations

from typing import Any


class EngineStepMetrics:
    def __init__(self) -> None:
        from dynamo_tpu.runtime import metric_names as mn
        from dynamo_tpu.runtime.metrics_core import COUNT_BUCKETS, MetricsRegistry

        self.registry = MetricsRegistry()
        self.step_duration = self.registry.histogram(
            mn.ENGINE_STEP_DURATION,
            "Device step wall time (one dispatch), by phase (prefill|decode)",
            ["phase"],
        )
        self.batch_occupancy = self.registry.histogram(
            mn.ENGINE_BATCH_OCCUPANCY,
            "Sequences packed into one device step, by phase",
            ["phase"],
            buckets=COUNT_BUCKETS,
        )
        self.prefill_tokens = self.registry.histogram(
            mn.ENGINE_STEP_PREFILL_TOKENS,
            "Prompt tokens processed per prefill step",
            buckets=COUNT_BUCKETS,
        )
        self.decode_tokens = self.registry.histogram(
            mn.ENGINE_STEP_DECODE_TOKENS,
            "Tokens emitted per decode step (fused multi-iteration burst)",
            buckets=COUNT_BUCKETS,
        )
        # Decode-tick pipelining (dispatch/reap split): host_gap is the
        # device wait the host injected between the previous burst's
        # readback completing and the next dispatch being enqueued — 0
        # whenever another burst was already queued on the device. The
        # depth-1 vs depth-2 comparison of this family IS the overlap win.
        self.host_gap = self.registry.histogram(
            mn.ENGINE_HOST_GAP,
            "Host-injected device wait between decode bursts "
            "(0 = the next burst was already in flight)",
        )
        self.inflight_depth = self.registry.histogram(
            mn.ENGINE_INFLIGHT_DEPTH,
            "Decode bursts in flight on the device at each dispatch "
            "(including the one being dispatched)",
            buckets=COUNT_BUCKETS,
        )

    def observe_prefill(self, duration_s: float, occupancy: int, tokens: int) -> None:
        self.step_duration.observe(duration_s, phase="prefill")
        self.batch_occupancy.observe(occupancy, phase="prefill")
        self.prefill_tokens.observe(tokens)

    def observe_decode(self, duration_s: float, occupancy: int, tokens: int) -> None:
        self.step_duration.observe(duration_s, phase="decode")
        self.batch_occupancy.observe(occupancy, phase="decode")
        self.decode_tokens.observe(tokens)

    def observe_host_gap(self, gap_s: float) -> None:
        self.host_gap.observe(gap_s)

    def observe_inflight(self, depth: int) -> None:
        self.inflight_depth.observe(depth)

    def host_gap_stats(self) -> tuple:
        """(count, total_seconds) observed on the host-gap family — the
        aggregate bench.py records as host_gap_ms."""
        return self.host_gap.snapshot_total()

    def render(self, openmetrics: bool = False) -> str:
        return self.registry.render(openmetrics=openmetrics)

    def register_metrics(self, server: Any) -> None:
        """Expose this engine's step families on a SystemStatusServer."""
        server.register_metrics(self.render)
