"""Engine step-loop metrics (runtime/metric_names.py ALL_ENGINE families).

Reference parity: the reference's backend ForwardPassMetrics / engine-side
Prometheus gauges — but for the step loop itself: how long each device
dispatch takes, how full the batch is, and how many tokens each step moved,
split prefill vs decode. These are the signals the planner's SLA math and
the ROADMAP's autoscaling direction need (step time × occupancy = achieved
throughput; prefill-vs-decode token mix = P/D balance).

One instance per engine object on a private registry (see
runtime/metrics_core.py for why not prometheus_client's global registry);
``render`` plugs into ``SystemStatusServer.register_metrics`` — wired by
``attach_engine`` for any engine exposing a ``step_metrics`` attribute.
"""

from __future__ import annotations

from typing import Any


class EngineStepMetrics:
    def __init__(self) -> None:
        from dynamo_tpu.runtime import metric_names as mn
        from dynamo_tpu.runtime.metrics_core import COUNT_BUCKETS, MetricsRegistry

        self.registry = MetricsRegistry()
        self.step_duration = self.registry.histogram(
            mn.ENGINE_STEP_DURATION,
            "Device step wall time (one dispatch), by phase (prefill|decode)",
            ["phase"],
        )
        self.batch_occupancy = self.registry.histogram(
            mn.ENGINE_BATCH_OCCUPANCY,
            "Sequences packed into one device step, by phase",
            ["phase"],
            buckets=COUNT_BUCKETS,
        )
        self.prefill_tokens = self.registry.histogram(
            mn.ENGINE_STEP_PREFILL_TOKENS,
            "Prompt tokens processed per prefill step",
            buckets=COUNT_BUCKETS,
        )
        self.decode_tokens = self.registry.histogram(
            mn.ENGINE_STEP_DECODE_TOKENS,
            "Tokens emitted per decode step (fused multi-iteration burst)",
            buckets=COUNT_BUCKETS,
        )

    def observe_prefill(self, duration_s: float, occupancy: int, tokens: int) -> None:
        self.step_duration.observe(duration_s, phase="prefill")
        self.batch_occupancy.observe(occupancy, phase="prefill")
        self.prefill_tokens.observe(tokens)

    def observe_decode(self, duration_s: float, occupancy: int, tokens: int) -> None:
        self.step_duration.observe(duration_s, phase="decode")
        self.batch_occupancy.observe(occupancy, phase="decode")
        self.decode_tokens.observe(tokens)

    def render(self, openmetrics: bool = False) -> str:
        return self.registry.render(openmetrics=openmetrics)

    def register_metrics(self, server: Any) -> None:
        """Expose this engine's step families on a SystemStatusServer."""
        server.register_metrics(self.render)
