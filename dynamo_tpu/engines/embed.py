"""Embedding engine: OpenAI /v1/embeddings over the native model family.

Reference parity: the reference serves embedding models through its engines
behind the same frontend route (http/service openai embeddings + model_type
"embedding" cards). Here a jitted encode (models/llama.py::encode —
mean-pooled final hidden states) serves batches of texts; shapes bucket to
powers of two for a bounded compile set.
"""

from __future__ import annotations

import functools
from typing import Any, AsyncIterator, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.runtime.device_observe import watched_jit
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _next_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


class EmbeddingEngine:
    """AsyncEngine for OpenAI embeddings requests (dict in, dict out)."""

    def __init__(
        self,
        config: ModelConfig,
        tokenizer: Any,
        *,
        params: Optional[Any] = None,
        max_batch: int = 32,
        max_length: int = 512,
        normalize: bool = True,
        seed: int = 0,
    ) -> None:
        self.config = config
        self.tokenizer = tokenizer
        self.max_batch = max_batch
        self.max_length = max_length
        self.normalize = normalize
        self.params = (
            params
            if params is not None
            else llama.init_params(config, jax.random.PRNGKey(seed))
        )
        # Signature count tracks the pow2 (batch, length) buckets —
        # bounded by design, so the default budget is plenty.
        self._encode = watched_jit(
            "embed.encode",
            jax.jit(functools.partial(llama.encode, config=config)),
        )
        self.embedded_texts = 0

    def _embed_batch(self, token_lists: List[List[int]]) -> np.ndarray:
        B = _next_pow2(len(token_lists))
        T = min(
            _next_pow2(max(len(t) for t in token_lists)), self.max_length
        )
        toks = np.zeros((B, T), dtype=np.int32)
        lens = np.zeros(B, dtype=np.int32)
        for i, ids in enumerate(token_lists):
            ids = ids[:T]
            toks[i, : len(ids)] = ids
            lens[i] = len(ids)
        out = self._encode(
            self.params, tokens=jnp.asarray(toks), lengths=jnp.asarray(lens)
        )
        vecs = np.asarray(out)[: len(token_lists)]
        if self.normalize:
            norms = np.linalg.norm(vecs, axis=-1, keepdims=True)
            vecs = vecs / np.maximum(norms, 1e-9)
        return vecs

    async def generate(self, request: Any, context: Any) -> AsyncIterator[Dict[str, Any]]:
        inputs = request.get("input")
        if isinstance(inputs, str):
            texts = [inputs]
        elif isinstance(inputs, list) and all(isinstance(t, str) for t in inputs):
            texts = inputs
        else:
            yield {"error": {"message": "'input' must be a string or list of strings",
                             "type": "invalid_request_error"}}
            return
        token_lists = [self.tokenizer.encode(t) or [0] for t in texts]
        data = []
        total_tokens = 0
        for off in range(0, len(token_lists), self.max_batch):
            chunk = token_lists[off : off + self.max_batch]
            vecs = self._embed_batch(chunk)
            for i, vec in enumerate(vecs):
                data.append(
                    {
                        "object": "embedding",
                        "index": off + i,
                        "embedding": [float(x) for x in vec],
                    }
                )
            total_tokens += sum(len(t) for t in chunk)
        self.embedded_texts += len(texts)
        yield {
            "object": "list",
            "model": request.get("model", self.config.name),
            "data": data,
            "usage": {"prompt_tokens": total_tokens, "total_tokens": total_tokens},
        }
