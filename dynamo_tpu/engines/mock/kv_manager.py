"""Block-granular KV manager with prefix caching and LRU eviction.

Reference parity: lib/mocker/src/kv_manager.rs:50 (KvManager) and
evictor.rs. Blocks live in three states: free, active (pinned by a running
sequence), or inactive (cached, evictable LRU). Prefix caching matches a new
request's chained block hashes against active+inactive blocks; matched
inactive blocks are re-activated without recompute.

Emits KV events (stored/removed) through a callback — the same event stream
real engines publish for the KV-aware router (ref: kv-event emission in
mocker + kv_router/publisher.rs).
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set


@dataclass
class KvEvent:
    kind: str  # "stored" | "removed" | "cleared"
    block_hashes: List[int] = field(default_factory=list)
    parent_hash: Optional[int] = None


EventCallback = Callable[[KvEvent], None]


@dataclass
class _Block:
    block_hash: int
    parent_hash: Optional[int]
    ref_count: int = 0


class KvManager:
    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        *,
        on_event: Optional[EventCallback] = None,
    ) -> None:
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._on_event = on_event
        self._blocks: Dict[int, _Block] = {}  # hash → block (active or cached)
        self._inactive: "OrderedDict[int, _Block]" = OrderedDict()  # LRU order
        self._used = 0  # count of distinct resident blocks

    # -- stats -------------------------------------------------------------

    @property
    def free_blocks(self) -> int:
        return self.num_blocks - self._used + len(self._inactive)

    @property
    def active_blocks(self) -> int:
        return self._used - len(self._inactive)

    @property
    def cached_blocks(self) -> int:
        return len(self._inactive)

    @property
    def usage(self) -> float:
        return self.active_blocks / self.num_blocks if self.num_blocks else 0.0

    def committed_view(self):
        """Read-only [(hash, parent_hash)] of every resident block, for
        KV-event re-sync (the radix tree tolerates replay order)."""
        return [(h, b.parent_hash) for h, b in self._blocks.items()]

    # -- prefix matching ---------------------------------------------------

    def match_prefix(self, block_hashes: Sequence[int]) -> int:
        """Leading blocks already resident (active or cached)."""
        n = 0
        for h in block_hashes:
            if h in self._blocks:
                n += 1
            else:
                break
        return n

    # -- allocation --------------------------------------------------------

    def _available_for(self, block_hashes: Sequence[int], matched: int) -> int:
        """Blocks obtainable for NEW allocations given that the matched prefix
        gets pinned (pinning removes matched-inactive blocks from the
        evictable set, so they must not be counted as free)."""
        matched_inactive = sum(1 for h in block_hashes[:matched] if h in self._inactive)
        return (self.num_blocks - self._used) + (len(self._inactive) - matched_inactive)

    def can_allocate(self, block_hashes: Sequence[int], extra_blocks: int = 0) -> bool:
        matched = self.match_prefix(block_hashes)
        needed = len(block_hashes) - matched + extra_blocks
        return needed <= self._available_for(block_hashes, matched)

    def allocate(self, block_hashes: Sequence[int]) -> Optional[int]:
        """Pin the chain for a sequence. Returns matched-prefix block count,
        or None if pool can't fit (caller keeps the request queued)."""
        matched = self.match_prefix(block_hashes)
        needed = len(block_hashes) - matched
        if needed > self._available_for(block_hashes, matched):
            return None
        # Reactivate / pin matched prefix.
        for h in block_hashes[:matched]:
            block = self._blocks[h]
            if block.ref_count == 0:
                self._inactive.pop(h, None)
            block.ref_count += 1
        # Allocate the rest, evicting LRU cached blocks as needed. A block
        # past the matched prefix can still be resident (eviction can punch
        # holes in a chain: the parent went, the child stayed) — pin it
        # instead of double-allocating.
        parent = block_hashes[matched - 1] if matched else None
        new_hashes: List[int] = []
        for h in block_hashes[matched:]:
            existing = self._blocks.get(h)
            if existing is not None:
                if existing.ref_count == 0:
                    self._inactive.pop(h, None)
                existing.ref_count += 1
                parent = h
                continue
            if self._used >= self.num_blocks:
                self._evict_one()
            block = _Block(block_hash=h, parent_hash=parent, ref_count=1)
            self._blocks[h] = block
            self._used += 1
            new_hashes.append(h)
            parent = h
        if new_hashes:
            self._emit(
                KvEvent(
                    kind="stored",
                    block_hashes=new_hashes,
                    parent_hash=block_hashes[matched - 1] if matched else None,
                )
            )
        return matched

    def extend(self, prev_hash: Optional[int], new_hash: int) -> bool:
        """Add one decode-grown block to a running sequence."""
        if new_hash in self._blocks:
            block = self._blocks[new_hash]
            if block.ref_count == 0:
                self._inactive.pop(new_hash, None)
            block.ref_count += 1
            return True
        if self._used >= self.num_blocks:
            if not self._inactive:
                return False
            self._evict_one()
        self._blocks[new_hash] = _Block(block_hash=new_hash, parent_hash=prev_hash, ref_count=1)
        self._used += 1
        self._emit(KvEvent(kind="stored", block_hashes=[new_hash], parent_hash=prev_hash))
        return True

    def release(self, block_hashes: Sequence[int]) -> None:
        """Sequence finished: unpin its chain; blocks become cached (LRU)."""
        for h in block_hashes:
            block = self._blocks.get(h)
            if block is None:
                continue
            block.ref_count -= 1
            if block.ref_count <= 0:
                block.ref_count = 0
                self._inactive[h] = block
                self._inactive.move_to_end(h)

    def clear(self) -> None:
        """Flush the reusable cache (ref: clear_kv_blocks route)."""
        evicted = list(self._inactive)
        for h in evicted:
            del self._blocks[h]
            self._used -= 1
        self._inactive.clear()
        if evicted:
            self._emit(KvEvent(kind="removed", block_hashes=evicted))
        self._emit(KvEvent(kind="cleared"))

    def _evict_one(self) -> None:
        if not self._inactive:
            raise RuntimeError("KV pool exhausted with no evictable blocks")
        h, _ = self._inactive.popitem(last=False)
        del self._blocks[h]
        self._used -= 1
        self._emit(KvEvent(kind="removed", block_hashes=[h]))

    def _emit(self, event: KvEvent) -> None:
        if self._on_event is not None:
            self._on_event(event)
