"""The mock engine: a deterministic fake worker for accelerator-free testing.

Reference parity: lib/mocker — continuous-batching Scheduler (scheduler.rs:248),
KvManager with prefix caching (kv_manager.rs:50), learned timing
(perf_model.rs), KV-event emission, MockEngineArgs (protocols.rs:88). This is
the centerpiece that lets router/disagg/planner e2e tests run whole clusters
on CPU (SURVEY §4).

Semantics:
  - requests enter a waiting queue; the scheduler admits them when the KV
    pool fits their prompt blocks (watermark-gated), honoring max_num_seqs;
  - prefill cost = base + per-token (scaled by speedup_ratio); prefix-cached
    blocks are skipped, exactly like a real paged engine;
  - each decode tick appends one token per running sequence with a simulated
    inter-token latency;
  - generated tokens are a deterministic function of the whole token PREFIX
    (a per-token hash fold), so tests can assert reproducibility AND a
    migrated/handed-off continuation (the frontend re-dispatches prompt +
    already-streamed tokens, llm/migration.py _carry_tokens) produces
    exactly the tokens a never-migrated oracle would — the same
    prefix-determinism contract the real engine's fold_in(seed, salt, pos)
    sampling keys give (crash-plane soak relies on this);
  - KV events (stored/removed) are emitted for router indexing.
"""

from __future__ import annotations

import asyncio
import collections
import hashlib
import logging
import time
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable, Dict, List, Optional

from dynamo_tpu.engines.mock.kv_manager import KvEvent, KvManager
from dynamo_tpu.llm.protocols.common import (
    BackendOutput,
    FinishReason,
    PreprocessedRequest,
)
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.tokens.blocks import compute_block_hashes
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_RNG_SEED = 0x9E3779B97F4A7C15


def _fold_token(state: int, token: int) -> int:
    """One step of the prefix hash fold: state_{p+1} = H(state_p || token).
    Folding the same token sequence from _RNG_SEED always lands on the
    same state, no matter how the sequence was split between 'prompt' and
    'generated' — the property migration carry needs."""
    return int.from_bytes(
        hashlib.blake2b(
            state.to_bytes(8, "little") + int(token).to_bytes(8, "little"),
            digest_size=8,
        ).digest(),
        "little",
    )


def _fold_tokens(state: int, tokens) -> int:
    for t in tokens:
        state = _fold_token(state, t)
    return state


@dataclass
class MockEngineArgs:
    """(ref: lib/mocker/src/protocols.rs:88 MockEngineArgs)"""

    block_size: int = 16
    num_kv_blocks: int = 1024
    max_num_seqs: int = 32
    watermark: float = 0.01  # fraction of blocks kept free
    speedup_ratio: float = 1.0  # >1 = faster than the modeled timings
    dp_size: int = 1
    vocab_size: int = 512
    enable_prefix_caching: bool = True
    # Timing model (seconds), loosely A100-class (ref: perf_model.rs)
    prefill_base_s: float = 0.02
    prefill_per_token_s: float = 0.00005
    decode_itl_s: float = 0.01
    # Echo mode: emit the prompt tokens back instead of PRNG tokens
    echo: bool = False


@dataclass
class _Sequence:
    request: PreprocessedRequest
    context: Context
    queue: "asyncio.Queue[Optional[BackendOutput]]"
    prompt_hashes: List[int]
    all_tokens: List[int]  # prompt + generated
    generated: List[int] = field(default_factory=list)
    held_hashes: List[int] = field(default_factory=list)
    prefilled: bool = False
    rng_state: int = 0


class MockEngine:
    """AsyncEngine over a simulated continuous-batching scheduler."""

    def __init__(
        self,
        args: Optional[MockEngineArgs] = None,
        *,
        on_kv_event: Optional[Callable[[KvEvent], None]] = None,
    ) -> None:
        self.args = args or MockEngineArgs()
        self.kv = KvManager(
            self.args.num_kv_blocks, self.args.block_size, on_event=on_kv_event
        )
        # Deque, not asyncio.Queue: preempted sequences go back to the FRONT
        # without the queue-swap race the round-1 version had.
        self._waiting: "collections.deque[_Sequence]" = collections.deque()
        self._running: List[_Sequence] = []
        self._loop_task: Optional[asyncio.Task] = None
        self._stopped = asyncio.Event()
        self._wake = asyncio.Event()
        self.steps = 0  # decode iterations executed (test observability)

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        if self._loop_task is None:
            self._loop_task = asyncio.get_running_loop().create_task(
                self._scheduler_loop(), name="mock-engine-scheduler"
            )

    async def stop(self) -> None:
        self._stopped.set()
        self._wake.set()
        if self._loop_task is not None:
            await self._loop_task
            self._loop_task = None

    # -- AsyncEngine -------------------------------------------------------

    async def generate(
        self, request: Any, context: Context
    ) -> AsyncIterator[BackendOutput]:
        await self.start()
        if isinstance(request, dict):
            request = PreprocessedRequest.from_dict(request)
        prompt = list(request.token_ids)
        if not prompt:
            yield BackendOutput(error="empty prompt", finish_reason=FinishReason.ERROR)
            return
        seq = _Sequence(
            request=request,
            context=context,
            queue=asyncio.Queue(),
            prompt_hashes=compute_block_hashes(prompt, self.args.block_size)
            if self.args.enable_prefix_caching
            else [],
            all_tokens=prompt,
            rng_state=_fold_tokens(_RNG_SEED, prompt),
        )
        self._waiting.append(seq)
        self._wake.set()
        while True:
            out = await seq.queue.get()
            if out is None:
                return
            yield out
            if out.finish_reason is not None:
                return

    # -- scheduler ---------------------------------------------------------

    def _sleep_time(self, seconds: float) -> float:
        return seconds / max(self.args.speedup_ratio, 1e-9)

    def _requeue(self, seq: _Sequence) -> None:
        self._waiting.appendleft(seq)

    async def _scheduler_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                await self._scheduler_tick()
            except asyncio.CancelledError:
                raise
            except Exception:  # never let a bug kill the scheduler
                logger.exception("mock scheduler tick failed")
                await asyncio.sleep(self._sleep_time(self.args.decode_itl_s))

        # Drain on stop — running AND still-waiting sequences, so no
        # generate() caller is left blocked forever.
        for seq in self._running:
            seq.queue.put_nowait(BackendOutput(finish_reason=FinishReason.CANCELLED))
        self._running.clear()
        while self._waiting:
            seq = self._waiting.popleft()
            seq.queue.put_nowait(BackendOutput(finish_reason=FinishReason.CANCELLED))

    async def _scheduler_tick(self) -> None:
        args = self.args
        watermark_blocks = int(args.num_kv_blocks * args.watermark)
        # Admit waiting sequences (continuous batching admission). The
        # watermark keeps headroom for decode growth; it is waived when the
        # engine is idle so an admissible request is never deadlocked.
        while len(self._running) < args.max_num_seqs and self._waiting:
            seq = self._waiting.popleft()
            if seq.context.stopped:
                seq.queue.put_nowait(BackendOutput(finish_reason=FinishReason.CANCELLED))
                continue
            if seq.prompt_hashes:
                if len(seq.prompt_hashes) > args.num_kv_blocks:
                    seq.queue.put_nowait(
                        BackendOutput(
                            error=(
                                f"prompt needs {len(seq.prompt_hashes)} KV blocks; "
                                f"pool has {args.num_kv_blocks}"
                            ),
                            finish_reason=FinishReason.ERROR,
                        )
                    )
                    continue
                headroom = watermark_blocks if self._running else 0
                if not self.kv.can_allocate(seq.prompt_hashes, extra_blocks=headroom):
                    self._requeue(seq)
                    break
                result = self.kv.allocate(seq.prompt_hashes)
                if result is None:
                    self._requeue(seq)
                    break
                matched = result
                seq.held_hashes = list(seq.prompt_hashes)
            else:
                matched = 0
            # Simulate prefill (skipping cached prefix).
            new_tokens = max(0, len(seq.request.token_ids) - matched * args.block_size)
            await asyncio.sleep(
                self._sleep_time(args.prefill_base_s + args.prefill_per_token_s * new_tokens)
            )
            seq.prefilled = True
            self._running.append(seq)

        if not self._running:
            # Idle (or blocked on KV space): wait for a wake-up or tick.
            self._wake.clear()
            try:
                await asyncio.wait_for(self._wake.wait(), timeout=0.05)
            except asyncio.TimeoutError:
                pass
            return

        # One decode tick for the whole batch.
        await asyncio.sleep(self._sleep_time(args.decode_itl_s))
        self.steps += 1
        still_running: List[_Sequence] = []
        for seq in self._running:
            finished = self._decode_step(seq)
            if not finished:
                still_running.append(seq)
        self._running = still_running

    def _decode_step(self, seq: _Sequence) -> bool:
        """Generate one token; returns True when the sequence finished."""
        if seq.context.stopped:
            self._finish(seq, FinishReason.CANCELLED)
            return True
        token = self._next_token(seq)
        seq.generated.append(token)
        seq.all_tokens.append(token)

        # Grow the KV chain when a block boundary is crossed.
        if (
            self.args.enable_prefix_caching
            and len(seq.all_tokens) % self.args.block_size == 0
        ):
            new_hashes = compute_block_hashes(
                seq.all_tokens[-self.args.block_size :],
                self.args.block_size,
                parent_hash=seq.held_hashes[-1] if seq.held_hashes else None,
            )
            if new_hashes and self.kv.extend(
                seq.held_hashes[-1] if seq.held_hashes else None, new_hashes[0]
            ):
                seq.held_hashes.extend(new_hashes)

        stop = seq.request.stop
        reason: Optional[FinishReason] = None
        min_ok = stop.min_tokens is None or len(seq.generated) >= stop.min_tokens
        if (
            not stop.ignore_eos
            and min_ok
            and token in (seq.request.eos_token_ids or [])
        ):
            reason = FinishReason.EOS
        elif min_ok and token in (stop.stop_token_ids or []):
            reason = FinishReason.STOP
        elif stop.max_tokens is not None and len(seq.generated) >= stop.max_tokens:
            reason = FinishReason.LENGTH

        seq.queue.put_nowait(
            BackendOutput(
                token_ids=[token],
                finish_reason=reason,
                cumulative_tokens=len(seq.generated),
            )
        )
        if reason is not None:
            self._finish(seq, reason, emit=False)
            return True
        return False

    def _next_token(self, seq: _Sequence) -> int:
        if self.args.echo:
            idx = len(seq.generated) % len(seq.request.token_ids)
            return seq.request.token_ids[idx]
        # Prefix-keyed: rng_state is a hash fold of EVERY token so far
        # (prompt + generated), so token p depends only on tokens[:p].
        # A carried re-dispatch (prompt + streamed tokens) therefore
        # continues the oracle's exact stream — xorshift64* whitens the
        # fold state into a token.
        x = seq.rng_state or _RNG_SEED
        x ^= (x << 13) & 0xFFFFFFFFFFFFFFFF
        x ^= x >> 7
        x ^= (x << 17) & 0xFFFFFFFFFFFFFFFF
        # Avoid emitting special/eos tokens (ids 0..3 in the tiny tokenizer).
        token = 4 + (x % (self.args.vocab_size - 4))
        seq.rng_state = _fold_token(seq.rng_state, token)
        return token

    def _finish(self, seq: _Sequence, reason: FinishReason, emit: bool = True) -> None:
        if seq.held_hashes:
            self.kv.release(seq.held_hashes)
            seq.held_hashes = []
        if emit:
            seq.queue.put_nowait(BackendOutput(finish_reason=reason))
