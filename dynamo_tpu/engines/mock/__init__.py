"""Mock engine (ref: lib/mocker)."""

from dynamo_tpu.engines.mock.engine import MockEngine, MockEngineArgs
from dynamo_tpu.engines.mock.kv_manager import KvEvent, KvManager

__all__ = ["KvEvent", "KvManager", "MockEngine", "MockEngineArgs"]
