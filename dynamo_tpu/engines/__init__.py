"""Inference engines: mock (CPU, deterministic) and the native JAX engine."""
