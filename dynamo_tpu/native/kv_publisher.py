"""ctypes wrapper for the C KV-event publisher (kv_publish.cpp).

Reference parity: lib/bindings/c — the C ABI external C++ engines use to
publish KV-cache events and load reports into the framework's planes. The
Python wrapper here exists for tests and as the embedding example; a real
C++ engine calls the `dyn_*` functions directly (see kv_publish.cpp).
"""

from __future__ import annotations

import ctypes
from typing import Optional, Sequence

from dynamo_tpu.native import _build_and_load
from dynamo_tpu.router.protocols import kv_events_topic, load_topic

_MASK64 = (1 << 64) - 1


def load_kv_publish_lib() -> Optional[ctypes.CDLL]:
    lib = _build_and_load(
        "dynkvpub", "kv_publish.cpp", extra_flags=("-l:libzmq.so.5",)
    )
    if lib is None:
        return None
    lib.dyn_kv_publisher_new.restype = ctypes.c_void_p
    lib.dyn_kv_publisher_new.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int,
    ]
    lib.dyn_kv_publish.restype = ctypes.c_int
    lib.dyn_kv_publish.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
        ctypes.c_uint64, ctypes.c_int, ctypes.c_uint64,
    ]
    lib.dyn_load_publish.restype = ctypes.c_int
    lib.dyn_load_publish.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
    ]
    lib.dyn_kv_publisher_free.argtypes = [ctypes.c_void_p]
    return lib


class CKvEventPublisher:
    """KV-event + load publishing through the native C library.

    ``xsub_endpoint``: the broker's XSUB address, e.g. "tcp://127.0.0.1:6181"
    (the first port of DYN_TPU_EVENT_PLANE_ADDR's host:xsub:xpub form).
    """

    def __init__(
        self,
        xsub_endpoint: str,
        namespace: str,
        component: str,
        worker_id: int,
        dp_rank: int = 0,
    ) -> None:
        self._lib = load_kv_publish_lib()
        if self._lib is None:
            raise RuntimeError("native kv_publish library unavailable")
        self._topic = kv_events_topic(namespace, component)
        self._load_topic = load_topic(namespace, component)
        self._handle = self._lib.dyn_kv_publisher_new(
            xsub_endpoint.encode(), self._topic.encode(),
            worker_id & _MASK64, dp_rank,
        )
        if not self._handle:
            raise RuntimeError(f"cannot connect PUB socket to {xsub_endpoint}")
        self._event_id = 0

    def publish_stored(
        self, block_hashes: Sequence[int], parent_hash: Optional[int] = None
    ) -> None:
        self._publish("stored", block_hashes, parent_hash)

    def publish_removed(self, block_hashes: Sequence[int]) -> None:
        self._publish("removed", block_hashes, None)

    def publish_cleared(self) -> None:
        self._publish("cleared", (), None)

    def _publish(self, kind, hashes, parent) -> None:
        self._event_id += 1
        n = len(hashes)
        arr = (ctypes.c_uint64 * max(n, 1))(*[h & _MASK64 for h in hashes])
        rc = self._lib.dyn_kv_publish(
            self._handle, kind.encode(), arr, n,
            (parent or 0) & _MASK64, 1 if parent is not None else 0,
            self._event_id,
        )
        if rc != 0:
            raise RuntimeError(f"dyn_kv_publish failed: {rc}")

    def publish_load(
        self, active_seqs: int, waiting: int, active_blocks: int,
        total_blocks: int,
    ) -> None:
        rc = self._lib.dyn_load_publish(
            self._handle, self._load_topic.encode(),
            active_seqs, waiting, active_blocks, total_blocks,
        )
        if rc != 0:
            raise RuntimeError(f"dyn_load_publish failed: {rc}")

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.dyn_kv_publisher_free(self._handle)
            self._handle = None

    def __del__(self):
        self.close()
