// C ABI KV-event publisher for external (C++) engines.
//
// Reference parity: lib/bindings/c (dynamo_llm_* functions, src/lib.rs:157,
// 172, 341) — the reference embeds its Rust runtime behind a C ABI so
// C++ engines (TRT-LLM) can publish KV-cache events and load metrics
// without a Python interpreter. Here the equivalent: this library speaks
// the framework's ZMQ event plane directly (PUB socket → the XSUB side of
// the broker, two-frame [topic | msgpack] messages, the exact wire format
// of runtime/events/zmq_plane.py) with a hand-rolled minimal msgpack
// encoder for the RouterEvent document (router/protocols.py).
//
// libzmq is loaded via the system's shared library (libzmq.so.5 is a
// stable C ABI); prototypes are declared here so no dev headers are
// needed at build time.
//
// API (ctypes-friendly, see native/kv_publisher.py):
//   void*  dyn_kv_publisher_new(endpoint, topic, worker_id, dp_rank)
//   int    dyn_kv_publish(pub, kind, hashes, n, parent, has_parent, event_id)
//   int    dyn_load_publish(pub, load_topic, active_seqs, waiting,
//                           active_blocks, total_blocks)
//   void   dyn_kv_publisher_free(pub)

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

// ---- minimal libzmq prototypes (ABI-stable since 4.x) ----------------------
extern "C" {
void *zmq_ctx_new(void);
int zmq_ctx_term(void *ctx);
void *zmq_socket(void *ctx, int type);
int zmq_close(void *socket);
int zmq_connect(void *socket, const char *endpoint);
int zmq_send(void *socket, const void *buf, size_t len, int flags);
int zmq_setsockopt(void *socket, int option, const void *val, size_t len);
}
static const int ZMQ_PUB_T = 1;
static const int ZMQ_SNDMORE_F = 2;
static const int ZMQ_LINGER_O = 17;

// ---- minimal msgpack encoder ----------------------------------------------
namespace {

void put_u8(std::string &b, uint8_t v) { b.push_back((char)v); }

void put_be(std::string &b, uint64_t v, int bytes) {
  for (int i = bytes - 1; i >= 0; --i) b.push_back((char)((v >> (8 * i)) & 0xff));
}

void pack_uint(std::string &b, uint64_t v) {
  put_u8(b, 0xcf);  // uint64 — widest form, always valid
  put_be(b, v, 8);
}

void pack_int(std::string &b, int64_t v) {
  if (v >= 0) return pack_uint(b, (uint64_t)v);
  put_u8(b, 0xd3);  // int64
  put_be(b, (uint64_t)v, 8);
}

void pack_str(std::string &b, const char *s) {
  size_t n = std::strlen(s);
  put_u8(b, 0xd9);  // str8 (all our strings are short)
  put_u8(b, (uint8_t)n);
  b.append(s, n);
}

void pack_map_header(std::string &b, uint32_t n) {
  put_u8(b, 0xdf);  // map32
  put_be(b, n, 4);
}

void pack_array_header(std::string &b, uint32_t n) {
  put_u8(b, 0xdd);  // array32
  put_be(b, n, 4);
}

void pack_nil(std::string &b) { put_u8(b, 0xc0); }

struct Publisher {
  void *ctx = nullptr;
  void *sock = nullptr;
  std::string topic;
  uint64_t worker_id = 0;
  int dp_rank = 0;
};

int send_two_frames(Publisher *p, const std::string &topic,
                    const std::string &payload) {
  if (zmq_send(p->sock, topic.data(), topic.size(), ZMQ_SNDMORE_F) < 0)
    return -1;
  if (zmq_send(p->sock, payload.data(), payload.size(), 0) < 0) return -2;
  return 0;
}

}  // namespace

// ---- C API -----------------------------------------------------------------
extern "C" {

void *dyn_kv_publisher_new(const char *xsub_endpoint, const char *topic,
                           uint64_t worker_id, int dp_rank) {
  auto *p = new Publisher();
  p->ctx = zmq_ctx_new();
  if (!p->ctx) { delete p; return nullptr; }
  p->sock = zmq_socket(p->ctx, ZMQ_PUB_T);
  if (!p->sock) { zmq_ctx_term(p->ctx); delete p; return nullptr; }
  int linger = 0;
  zmq_setsockopt(p->sock, ZMQ_LINGER_O, &linger, sizeof linger);
  if (zmq_connect(p->sock, xsub_endpoint) != 0) {
    zmq_close(p->sock); zmq_ctx_term(p->ctx); delete p; return nullptr;
  }
  p->topic = topic;
  p->worker_id = worker_id;
  p->dp_rank = dp_rank;
  return p;
}

// kind: "stored" | "removed" | "cleared". Returns 0 on success.
int dyn_kv_publish(void *pub, const char *kind, const uint64_t *hashes,
                   int n_hashes, uint64_t parent_hash, int has_parent,
                   uint64_t event_id) {
  auto *p = (Publisher *)pub;
  if (!p || !p->sock) return -3;
  std::string b;
  b.reserve(64 + 9 * (size_t)(n_hashes > 0 ? n_hashes : 0));
  pack_map_header(b, 6);  // RouterEvent fields (router/protocols.py:29)
  pack_str(b, "worker_id");   pack_uint(b, p->worker_id);
  pack_str(b, "kind");        pack_str(b, kind);
  pack_str(b, "block_hashes");
  pack_array_header(b, (uint32_t)(n_hashes > 0 ? n_hashes : 0));
  for (int i = 0; i < n_hashes; ++i) pack_uint(b, hashes[i]);
  pack_str(b, "parent_hash");
  if (has_parent) pack_uint(b, parent_hash); else pack_nil(b);
  pack_str(b, "dp_rank");     pack_int(b, p->dp_rank);
  pack_str(b, "event_id");    pack_uint(b, event_id);
  return send_two_frames(p, p->topic, b);
}

// Load report (LoadSnapshot fields, router/protocols.py:52 — unknown keys
// are dropped by from_dict, so only real fields are sent).
int dyn_load_publish(void *pub, const char *load_topic, int active_seqs,
                     int waiting, int active_blocks, int total_blocks) {
  auto *p = (Publisher *)pub;
  if (!p || !p->sock) return -3;
  std::string b;
  pack_map_header(b, 6);
  pack_str(b, "worker_id");     pack_uint(b, p->worker_id);
  pack_str(b, "dp_rank");       pack_int(b, p->dp_rank);
  pack_str(b, "active_seqs");   pack_int(b, active_seqs);
  pack_str(b, "waiting");       pack_int(b, waiting);
  pack_str(b, "active_blocks"); pack_int(b, active_blocks);
  pack_str(b, "total_blocks");  pack_int(b, total_blocks);
  return send_two_frames(p, std::string(load_topic), b);
}

void dyn_kv_publisher_free(void *pub) {
  auto *p = (Publisher *)pub;
  if (!p) return;
  if (p->sock) zmq_close(p->sock);
  if (p->ctx) zmq_ctx_term(p->ctx);
  delete p;
}

}  // extern "C"
