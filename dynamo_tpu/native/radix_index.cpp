// Native radix/prefix index over chained block hashes.
//
// Reference parity: lib/kv-router/src/radix_tree.rs (RadixTree — the
// router's hottest data structure: every request consults it, every KV
// event mutates it). The reference keeps this in Rust for the same reason
// this lives in C++: the per-event cost is pointer-chasing and hash-map
// churn that Python does 20-50x slower under load. Semantics mirror
// dynamo_tpu/tokens/radix.py exactly (the Python tree remains the
// reference implementation and fallback).
//
// Build (see dynamo_tpu/native/__init__.py, which invokes this on demand):
//   g++ -O2 -shared -fPIC -std=c++17 radix_index.cpp -o libdynradix.so
//
// Concurrency: single-writer — the asyncio loop applies events and runs
// queries from one thread, matching the Rust indexer's single consumer
// task. No internal locking.

#include <cstdint>
#include <cstddef>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

struct Node {
    uint64_t hash;
    Node* parent = nullptr;
    std::unordered_map<uint64_t, Node*> children;
    std::unordered_set<uint32_t> workers;
};

struct Tree {
    Node root;
    std::unordered_map<uint64_t, Node*> nodes;       // hash -> node
    std::unordered_map<uint32_t, std::unordered_set<uint64_t>> worker_blocks;

    ~Tree() {
        for (auto& [h, n] : nodes) delete n;
    }

    void maybe_prune(Node* node) {
        while (node != nullptr && node != &root && node->workers.empty() &&
               node->children.empty()) {
            Node* parent = node->parent;
            if (parent != nullptr) parent->children.erase(node->hash);
            nodes.erase(node->hash);
            delete node;
            node = parent;
        }
    }
};

}  // namespace

extern "C" {

void* radix_new() { return new Tree(); }

void radix_free(void* t) { delete static_cast<Tree*>(t); }

void radix_store(void* tp, uint32_t worker, uint64_t parent_hash,
                 int has_parent, const uint64_t* hashes, size_t n) {
    Tree* t = static_cast<Tree*>(tp);
    Node* node;
    if (!has_parent) {
        node = &t->root;
    } else {
        auto it = t->nodes.find(parent_hash);
        if (it != t->nodes.end()) {
            node = it->second;
        } else {
            // Parent unknown (events replayed out of order): detached root,
            // reachable through the flat map (radix.py store()).
            node = new Node{parent_hash};
            t->nodes.emplace(parent_hash, node);
        }
    }
    auto& held = t->worker_blocks[worker];
    for (size_t i = 0; i < n; i++) {
        uint64_t h = hashes[i];
        Node* child;
        auto cit = node->children.find(h);
        if (cit != node->children.end()) {
            child = cit->second;
        } else {
            auto nit = t->nodes.find(h);
            if (nit != t->nodes.end()) {
                child = nit->second;
                child->parent = node;
            } else {
                child = new Node{h, node};
                t->nodes.emplace(h, child);
            }
            node->children.emplace(h, child);
        }
        child->workers.insert(worker);
        held.insert(h);
        node = child;
    }
}

void radix_remove(void* tp, uint32_t worker, const uint64_t* hashes, size_t n) {
    Tree* t = static_cast<Tree*>(tp);
    auto wit = t->worker_blocks.find(worker);
    for (size_t i = 0; i < n; i++) {
        uint64_t h = hashes[i];
        auto it = t->nodes.find(h);
        if (it != t->nodes.end()) {
            it->second->workers.erase(worker);
            t->maybe_prune(it->second);
        }
        if (wit != t->worker_blocks.end()) wit->second.erase(h);
    }
}

void radix_remove_worker(void* tp, uint32_t worker) {
    Tree* t = static_cast<Tree*>(tp);
    auto wit = t->worker_blocks.find(worker);
    if (wit == t->worker_blocks.end()) return;
    // Copy: pruning mutates the held set's source nodes.
    std::vector<uint64_t> held(wit->second.begin(), wit->second.end());
    t->worker_blocks.erase(wit);
    for (uint64_t h : held) {
        auto it = t->nodes.find(h);
        if (it != t->nodes.end()) {
            it->second->workers.erase(worker);
            t->maybe_prune(it->second);
        }
    }
}

size_t radix_num_blocks(void* tp) {
    return static_cast<Tree*>(tp)->nodes.size();
}

size_t radix_worker_block_count(void* tp, uint32_t worker) {
    Tree* t = static_cast<Tree*>(tp);
    auto it = t->worker_blocks.find(worker);
    return it == t->worker_blocks.end() ? 0 : it->second.size();
}

// Walk the chain from the root; per-worker score = contiguous leading
// blocks held (a hole ends a worker's run — radix.py find_matches).
// Returns the number of (worker, score) pairs written; *matched_blocks
// gets the deepest score.
size_t radix_find_matches(void* tp, const uint64_t* hashes, size_t n,
                          uint32_t* out_workers, uint32_t* out_scores,
                          size_t max_out, uint32_t* matched_blocks) {
    Tree* t = static_cast<Tree*>(tp);
    Node* node = &t->root;
    std::unordered_map<uint32_t, uint32_t> scores;
    std::unordered_set<uint32_t> active;
    uint32_t depth = 0;
    for (size_t i = 0; i < n; i++) {
        auto it = node->children.find(hashes[i]);
        if (it == node->children.end()) break;
        Node* child = it->second;
        depth++;
        if (depth == 1) {
            active = child->workers;
        } else {
            for (auto w = active.begin(); w != active.end();) {
                if (child->workers.count(*w) == 0) w = active.erase(w);
                else ++w;
            }
        }
        if (active.empty()) break;
        for (uint32_t w : active) scores[w] = depth;
        node = child;
    }
    uint32_t best = 0;
    size_t count = 0;
    for (auto& [w, s] : scores) {
        if (count < max_out) {
            out_workers[count] = w;
            out_scores[count] = s;
            count++;
        }
        if (s > best) best = s;
    }
    *matched_blocks = best;
    return count;
}

}  // extern "C"
