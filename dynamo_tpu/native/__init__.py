"""Native (C++) runtime components with build-on-demand + Python fallback.

Reference parity: the reference's runtime hot paths are Rust/C++ (the
kv-router indexer, tokens crate, runtime core); the compute path here is
JAX/XLA, and these extensions cover the non-device hot paths. Each native
component has a pure-Python reference implementation that remains the
fallback (and the oracle in tests), so the framework never hard-requires a
toolchain at runtime.

Build model: g++ compiles the .cpp into a shared library under
``native/_build`` on first use (~1s, cached by source mtime); set
``DYN_TPU_NATIVE=0`` to force the Python fallbacks.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

from dynamo_tpu import config
from dynamo_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# Declared in the canonical registry (config.py).
NATIVE = config.NATIVE

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_HERE, "_build")
_LOAD_CACHE: dict = {}


def _build_and_load(
    name: str, source: str, extra_flags: tuple = ()
) -> Optional[ctypes.CDLL]:
    """Compile ``source`` (under native/) to a cached .so and dlopen it."""
    if name in _LOAD_CACHE:
        return _LOAD_CACHE[name]
    lib = None
    if NATIVE.get():
        src = os.path.join(_HERE, source)
        # Flags participate in the artifact name: changing link flags must
        # rebuild, not reuse a stale .so built differently.
        import hashlib

        tag = (
            "-" + hashlib.md5(" ".join(extra_flags).encode()).hexdigest()[:8]
            if extra_flags
            else ""
        )
        out = os.path.join(_BUILD_DIR, f"lib{name}{tag}.so")
        try:
            if not os.path.exists(out) or os.path.getmtime(out) < os.path.getmtime(src):
                os.makedirs(_BUILD_DIR, exist_ok=True)
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", src,
                     "-o", out, *extra_flags],
                    check=True, capture_output=True, timeout=120,
                )
                logger.info("built native component %s", name)
            lib = ctypes.CDLL(out)
        except (OSError, subprocess.SubprocessError) as exc:
            logger.warning(
                "native component %s unavailable (%s); using Python fallback",
                name, exc,
            )
            lib = None
    _LOAD_CACHE[name] = lib
    return lib


def load_radix_lib() -> Optional[ctypes.CDLL]:
    lib = _build_and_load("dynradix", "radix_index.cpp")
    if lib is None:
        return None
    u64p = ctypes.POINTER(ctypes.c_uint64)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    lib.radix_new.restype = ctypes.c_void_p
    lib.radix_free.argtypes = [ctypes.c_void_p]
    lib.radix_store.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint64, ctypes.c_int,
        u64p, ctypes.c_size_t,
    ]
    lib.radix_remove.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, u64p, ctypes.c_size_t
    ]
    lib.radix_remove_worker.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.radix_num_blocks.argtypes = [ctypes.c_void_p]
    lib.radix_num_blocks.restype = ctypes.c_size_t
    lib.radix_worker_block_count.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.radix_worker_block_count.restype = ctypes.c_size_t
    lib.radix_find_matches.argtypes = [
        ctypes.c_void_p, u64p, ctypes.c_size_t, u32p, u32p, ctypes.c_size_t,
        u32p,
    ]
    lib.radix_find_matches.restype = ctypes.c_size_t
    return lib
