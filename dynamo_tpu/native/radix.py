"""ctypes wrapper for the C++ radix index (radix_index.cpp).

Same surface as tokens/radix.py::RadixTree; ``make_radix_tree()`` returns
the native tree when the extension is available, the Python one otherwise.
Worker keys (worker_id, dp_rank) are interned to dense uint32 handles on
the Python side (C++ sees opaque worker handles, matching the reference's
WorkerId indirection).
"""

from __future__ import annotations

import ctypes
from typing import Dict, Iterable, List, Optional, Sequence

from dynamo_tpu.native import load_radix_lib
from dynamo_tpu.tokens.radix import OverlapScores, RadixTree, WorkerKey

_MASK64 = (1 << 64) - 1
_MAX_WORKERS_OUT = 4096


def _hash_array(hashes: Sequence[int]):
    n = len(hashes)
    arr = (ctypes.c_uint64 * n)(*[h & _MASK64 for h in hashes])
    return arr, n


class NativeRadixTree:
    def __init__(self, lib) -> None:
        self._lib = lib
        self._tree = lib.radix_new()
        self._intern: Dict[WorkerKey, int] = {}
        self._rev: List[WorkerKey] = []

    def __del__(self):
        tree = getattr(self, "_tree", None)
        if tree:
            self._lib.radix_free(tree)
            self._tree = None

    def _wid(self, worker: WorkerKey) -> int:
        wid = self._intern.get(worker)
        if wid is None:
            wid = len(self._rev)
            self._intern[worker] = wid
            self._rev.append(worker)
        return wid

    # -- stats -------------------------------------------------------------

    @property
    def num_blocks(self) -> int:
        return int(self._lib.radix_num_blocks(self._tree))

    @property
    def workers(self) -> List[WorkerKey]:
        return sorted(
            w for w in self._intern if self.worker_block_count(w) > 0 or True
        )

    def worker_block_count(self, worker: WorkerKey) -> int:
        wid = self._intern.get(worker)
        if wid is None:
            return 0
        return int(self._lib.radix_worker_block_count(self._tree, wid))

    # -- updates -----------------------------------------------------------

    def store(
        self,
        worker: WorkerKey,
        block_hashes: Sequence[int],
        parent_hash: Optional[int] = None,
    ) -> None:
        arr, n = _hash_array(block_hashes)
        self._lib.radix_store(
            self._tree, self._wid(worker),
            (parent_hash or 0) & _MASK64, int(parent_hash is not None),
            arr, n,
        )

    def remove(self, worker: WorkerKey, block_hashes: Iterable[int]) -> None:
        arr, n = _hash_array(list(block_hashes))
        self._lib.radix_remove(self._tree, self._wid(worker), arr, n)

    def remove_worker(self, worker: WorkerKey) -> None:
        wid = self._intern.get(worker)
        if wid is not None:
            self._lib.radix_remove_worker(self._tree, wid)

    def clear_worker(self, worker: WorkerKey) -> None:
        self.remove_worker(worker)
        self._wid(worker)  # stays known, holding nothing (radix.py parity)

    # -- lookup ------------------------------------------------------------

    def find_matches(self, block_hashes: Sequence[int]) -> OverlapScores:
        arr, n = _hash_array(block_hashes)
        out_w = (ctypes.c_uint32 * _MAX_WORKERS_OUT)()
        out_s = (ctypes.c_uint32 * _MAX_WORKERS_OUT)()
        matched = ctypes.c_uint32(0)
        count = self._lib.radix_find_matches(
            self._tree, arr, n, out_w, out_s, _MAX_WORKERS_OUT,
            ctypes.byref(matched),
        )
        result = OverlapScores()
        for i in range(count):
            result.scores[self._rev[out_w[i]]] = int(out_s[i])
        result.matched_blocks = int(matched.value)
        return result


def make_radix_tree():
    """Native tree when available, Python RadixTree otherwise."""
    lib = load_radix_lib()
    if lib is not None:
        return NativeRadixTree(lib)
    return RadixTree()
