import asyncio, time, os, json
os.environ.setdefault("BENCH_REQUESTS", "128")
import numpy as np
import jax
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
import bench as B
from dynamo_tpu.engines.tpu import engine as eng_mod

times = {"decode": 0.0, "prefill": 0.0, "decode_n": 0, "prefill_n": 0}
orig_rd = eng_mod.JaxEngine._run_decode
orig_rs = eng_mod.JaxEngine._run_step
def rd(self, *a, **k):
    t0 = time.perf_counter(); r = orig_rd(self, *a, **k)
    times["decode"] += time.perf_counter()-t0; times["decode_n"] += 1
    return r
def rs(self, *a, **k):
    t0 = time.perf_counter(); r = orig_rs(self, *a, **k)
    times["prefill"] += time.perf_counter()-t0; times["prefill_n"] += 1
    return r
eng_mod.JaxEngine._run_decode = rd
eng_mod.JaxEngine._run_step = rs

t0 = time.perf_counter()
asyncio.run(B.run_bench())
wall = time.perf_counter()-t0
print(json.dumps({**times, "total_wall_incl_warmup": round(wall,2),
                  "decode_ms_per_dispatch": round(times["decode"]/max(times["decode_n"],1)*1000,1),
                  "prefill_ms_per_dispatch": round(times["prefill"]/max(times["prefill_n"],1)*1000,1)}))
