import asyncio, time, os, json
os.environ.setdefault("BENCH_CONCURRENCY", "128")
os.environ.setdefault("BENCH_REQUESTS", "256")
import jax
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
import bench as B
from dynamo_tpu.engines.tpu import engine as eng_mod

events = []
orig_rd = eng_mod.JaxEngine._run_decode
orig_rs = eng_mod.JaxEngine._run_step
def rd(self, *a, **k):
    t0 = time.perf_counter(); r = orig_rd(self, *a, **k)
    events.append(("decode", t0, time.perf_counter()-t0)); return r
def rs(self, *a, **k):
    t0 = time.perf_counter(); r = orig_rs(self, *a, **k)
    events.append(("prefill", t0, time.perf_counter()-t0)); return r
eng_mod.JaxEngine._run_decode = rd
eng_mod.JaxEngine._run_step = rs
asyncio.run(B.run_bench())
# steady state = events in the last 60% of the timeline
t_lo = events[0][1] + 0.4*(events[-1][1]-events[0][1])
for kind in ("decode", "prefill"):
    sel = [d for k,t,d in events if k==kind and t>=t_lo]
    if sel:
        print(f"{kind}: n={len(sel)} avg={sum(sel)/len(sel)*1000:.1f}ms max={max(sel)*1000:.1f}ms")
# device-busy fraction over steady window
busy = sum(d for k,t,d in events if t>=t_lo)
span = events[-1][1]+events[-1][2]-t_lo
print(f"device-dispatch busy: {busy:.2f}s of {span:.2f}s ({busy/span*100:.0f}%)")
